"""Token-boundary interruption: interrupt/resume, preemption, drain, chaos.

The tentpole contract (ISSUE 19): ``interrupt(rid, reason)`` stops a
sequence at the next decode step with its KV retained PINNED and
version-tagged; the re-issue of prompt+accumulated resumes with zero
re-prefill (token-identical when no commit intervened), or — across a
staged weight commit — recomputes only the uncovered suffix and continues
on the NEW weights with per-token ``versions`` spanning the commit.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from areal_tpu.api.cli_args import GenerationHyperparameters, JaxGenConfig
from areal_tpu.inference.engine import GenerationEngine
from areal_tpu.models.config import tiny_config
from areal_tpu.models.lm import init_params
from areal_tpu.utils import chaos


@pytest.fixture(scope="module")
def model():
    cfg = tiny_config(
        vocab_size=128,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
    )
    params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    return cfg, params


def make_engine(model, **kw):
    cfg, params = model
    defaults = dict(
        max_batch_size=4,
        max_seq_len=1024,
        prefill_chunk=64,
        decode_steps_per_call=4,
        dtype="float32",
    )
    defaults.update(kw)
    eng = GenerationEngine(
        JaxGenConfig(**defaults), model_config=cfg, params=params
    )
    eng.start()
    return eng


def run_request(eng, rid, prompt, gconfig, timeout=120.0, **submit_kw):
    done = threading.Event()
    out = {}

    def cb(r):
        out["r"] = r
        done.set()

    eng.submit(rid, prompt, gconfig, cb, **submit_kw)
    assert done.wait(timeout), "generation timed out"
    return out["r"]


def submit_async(eng, rid, prompt, gconfig, **submit_kw):
    done = threading.Event()
    out = {}
    eng.submit(
        rid, prompt, gconfig,
        lambda r: (out.update(r=r), done.set()),
        **submit_kw,
    )
    return done, out


def wait_tokens(eng, rid, n=1, timeout=60.0):
    """Block until ``rid`` is running and has emitted >= n tokens."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for seq in eng.slots:
            if seq is not None and seq.rid == rid and len(seq.out_tokens) >= n:
                return
        time.sleep(0.01)
    raise AssertionError(f"rid={rid} never reached {n} emitted token(s)")


def _staged_commit(eng, params, version):
    """One PR 5-style staged weight commit (stage off-thread, fenced flip)."""
    named = {}

    def walk(node, prefix):
        for k, v in node.items():
            path = f"{prefix}.{k}" if prefix else k
            if isinstance(v, dict):
                walk(v, path)
            else:
                named[path] = np.asarray(v)

    walk(params, "")
    eng.stage_weight_chunk(named, version=version)
    eng.commit_staged_weights(version)


def test_interrupt_mid_decode_exact_resume(model):
    """interrupt() answers with stop_reason="interrupt" + pinned retained
    KV; the prompt+accumulated re-issue resumes with ZERO re-prefill and
    the greedy splice is token-identical to an uninterrupted run."""
    eng = make_engine(model)
    try:
        prompt = [5, 9, 3, 7, 2]
        g = GenerationHyperparameters(max_new_tokens=200, greedy=True)
        full = run_request(eng, "ref", prompt, g)
        assert len(full.output_tokens) == 200

        done, out = submit_async(eng, "irq", prompt, g)
        wait_tokens(eng, "irq")
        eng.interrupt("irq", reason="manual")
        assert done.wait(30)
        part = out["r"]
        assert part.stop_reason == "interrupt"
        assert 0 < len(part.output_tokens) < 200
        with eng._retained_lock:
            ent = eng._retained["irq"]
        assert ent.pinned and ent.version == 0
        ss = eng.serving_stats()
        assert ss["retained_kv_slots"] == 1
        assert ss["retained_kv_bytes"] > 0
        assert ss["interrupts_total"] == 1
        assert eng.interrupts_by_reason == {"manual": 1}

        prefills_before = eng.prefill_count
        cont = run_request(
            eng,
            "irq",
            prompt + list(part.output_tokens),
            GenerationHyperparameters(
                max_new_tokens=200 - len(part.output_tokens), greedy=True
            ),
        )
        assert list(part.output_tokens) + list(cont.output_tokens) == list(
            full.output_tokens
        )
        assert eng.prefill_count == prefills_before  # zero re-prefill
        ss = eng.serving_stats()
        assert ss["retained_kv_slots"] == 0  # no retained slot leaks
        assert ss["resumed_total"] == 1
        assert ss["resumed_tokens_total"] > 0
        assert ss["resumed_across_commit_total"] == 0
    finally:
        eng.stop()


def test_interrupt_resume_across_staged_commit_versions_span(model):
    """The headline: interrupt -> staged commit -> resume. The retained
    prefix keeps its old-version KV (accepted staleness), decode continues
    on the NEW weights, and the spliced per-token versions span the
    commit — exactly the trajectory shape decoupled PPO trains on."""
    cfg, params = model
    eng = make_engine(model)
    try:
        prompt = [4, 8, 15, 16, 23, 42]
        g = GenerationHyperparameters(max_new_tokens=300, greedy=True)
        done, out = submit_async(eng, "span", prompt, g)
        wait_tokens(eng, "span", n=2)
        eng.interrupt("span", reason="weight_swap")
        assert done.wait(30)
        part = out["r"]
        assert part.stop_reason == "interrupt"
        assert part.output_versions == [0] * len(part.output_tokens)

        new_params = jax.tree.map(lambda x: x * 1.03, params)
        _staged_commit(eng, new_params, version=1)
        assert eng.get_version() == 1

        prefills_before = eng.prefill_count
        cont = run_request(
            eng,
            "span",
            prompt + list(part.output_tokens),
            GenerationHyperparameters(max_new_tokens=20, greedy=True),
        )
        assert len(cont.output_tokens) == 20
        # every resumed token decoded under the committed weights
        assert cont.output_versions == [1] * 20
        # client-side splice (what the trainer sees): versions span the commit
        spliced = list(part.output_versions) + list(cont.output_versions)
        assert set(spliced) == {0, 1}
        assert spliced == sorted(spliced)  # monotonic across the commit
        assert eng.prefill_count == prefills_before  # still zero re-prefill
        assert eng.resumed_across_commit_total == 1
        ss = eng.serving_stats()
        assert ss["resumed_across_commit_total"] == 1
        assert ss["retained_kv_slots"] == 0
    finally:
        eng.stop()


def test_resume_recomputes_only_uncovered_suffix(model):
    """A re-issue LONGER than the retained coverage (the failover splice:
    tokens decoded on a peer come back as prompt) recomputes only the
    uncovered suffix — no full re-prefill — and the greedy continuation
    stays token-identical to the uninterrupted reference."""
    eng = make_engine(model)
    try:
        prompt = [7, 3, 11, 2, 19]
        g = GenerationHyperparameters(max_new_tokens=400, greedy=True)
        ref = run_request(eng, "sref", prompt, g)
        assert len(ref.output_tokens) == 400

        done, out = submit_async(eng, "sfx", prompt, g)
        wait_tokens(eng, "sfx")
        eng.interrupt("sfx", reason="drain")
        assert done.wait(30)
        part = out["r"]
        k = len(part.output_tokens)
        assert part.stop_reason == "interrupt"
        assert list(part.output_tokens) == list(ref.output_tokens[:k])
        assert k + 5 < 400, "interrupt landed too late for a suffix resume"

        # simulate 5 tokens decoded elsewhere: the re-issue covers MORE
        # than the retained KV, so resume must extend by exactly 5 tokens
        m = 5
        extra = list(ref.output_tokens[k: k + m])
        prefills_before = eng.prefill_count
        cont = run_request(
            eng,
            "sfx",
            prompt + list(part.output_tokens) + extra,
            GenerationHyperparameters(max_new_tokens=400 - k - m, greedy=True),
        )
        assert list(cont.output_tokens) == list(ref.output_tokens[k + m:])
        assert eng.prefill_count == prefills_before
        assert eng.resume_suffix_recomputed_tokens_total == m
        assert eng.serving_stats()["retained_kv_slots"] == 0
    finally:
        eng.stop()


def test_retained_ttl_reaper(model):
    """Hygiene satellite: a disconnected client's retained entry is reaped
    after retained_kv_ttl_seconds instead of pinning KV until LRU
    pressure, and the reap is visible in serving_stats()."""
    eng = make_engine(model, retained_kv_ttl_seconds=0.2)
    try:
        prompt = [1, 2, 3, 4]
        done, out = submit_async(
            eng, "leak", prompt,
            GenerationHyperparameters(max_new_tokens=300, greedy=True),
        )
        wait_tokens(eng, "leak")
        eng.interrupt("leak", reason="manual")
        assert done.wait(30)
        assert out["r"].stop_reason == "interrupt"
        assert eng.serving_stats()["retained_kv_slots"] == 1

        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            # the reaper runs on the engine loop; poke it awake
            eng._wake.set()
            if eng.serving_stats()["retained_kv_slots"] == 0:
                break
            time.sleep(0.05)
        ss = eng.serving_stats()
        assert ss["retained_kv_slots"] == 0
        assert ss["retained_kv_reaped_total"] == 1
        with eng._retained_lock:
            assert "leak" not in eng._retained
    finally:
        eng.stop()


def test_priority_preemption_requeues_victim(model):
    """A strictly-higher-priority request that cannot be admitted preempts
    the lowest-priority victim: the victim's KV is retained pinned, it
    requeues at its original position WITHOUT a client-visible response,
    and resumes with zero recompute — its final output is token-identical
    to an uninterrupted run."""
    eng = make_engine(
        model,
        max_batch_size=2,
        # one 128-token page is the whole budget: while the victim holds
        # its block, ANY new admission fails admission control and must
        # preempt to proceed
        admission_token_budget=128,
    )
    try:
        v_prompt = [5, 9, 3, 7, 2]
        h_prompt = [60, 61, 62]
        g = GenerationHyperparameters(max_new_tokens=100, greedy=True)
        v_ref = run_request(eng, "vref", v_prompt, g)
        h_ref = run_request(eng, "href", h_prompt, g)

        v_done, v_out = submit_async(eng, "victim", v_prompt, g, priority=0)
        wait_tokens(eng, "victim")
        h_done, h_out = submit_async(eng, "vip", h_prompt, g, priority=5)
        assert h_done.wait(60)
        assert v_done.wait(60)

        assert eng.preemptions_total == 1
        assert eng.interrupts_by_reason.get("preempt") == 1
        # the victim's client saw ONE response with the FULL output: the
        # preemption round-trip (retain pinned -> requeue -> exact resume)
        # was invisible except in the counters
        v = v_out["r"]
        assert v.stop_reason == v_ref.stop_reason
        assert list(v.output_tokens) == list(v_ref.output_tokens)
        assert list(h_out["r"].output_tokens) == list(h_ref.output_tokens)
        ss = eng.serving_stats()
        assert ss["preemptions_total"] == 1
        assert ss["resumed_total"] >= 1
        assert ss["retained_kv_slots"] == 0
    finally:
        eng.stop()


def test_interrupt_queued_request_answers_immediately(model):
    """A rid still waiting in the admission queue answers its interrupt
    with zero tokens instead of waiting for a slot."""
    eng = make_engine(model, max_batch_size=1)
    try:
        g = GenerationHyperparameters(max_new_tokens=500, greedy=True)
        a_done, a_out = submit_async(eng, "hog", [1, 2, 3], g)
        wait_tokens(eng, "hog")
        b_done, b_out = submit_async(eng, "queued", [4, 5, 6], g)
        eng.interrupt("queued", reason="manual")
        assert b_done.wait(10)
        assert b_out["r"].stop_reason == "interrupt"
        assert b_out["r"].output_tokens == []
        eng.interrupt("hog", reason="manual")
        assert a_done.wait(10)
        assert a_out["r"].stop_reason == "interrupt"
    finally:
        eng.stop()


def test_interrupt_all_drain_is_bounded(model):
    """interrupt_all("drain") with every slot mid-decode completes in
    ~one decode chunk, not max-generation-length; every sequence answers
    "interrupt" with retained KV, and exact resumes drain the retained
    map back to zero (the acceptance invariant)."""
    eng = make_engine(model)
    try:
        g = GenerationHyperparameters(max_new_tokens=900, greedy=True)
        waiters = []
        for i in range(4):
            d, o = submit_async(eng, f"d{i}", [10 + i, 20 + i, 3], g)
            waiters.append((d, o))
        for i in range(4):
            wait_tokens(eng, f"d{i}")
        assert eng.n_running == 4

        t0 = time.monotonic()
        eng.interrupt_all("drain")
        wall = time.monotonic() - t0
        for d, _ in waiters:
            assert d.wait(10)
        # bounded by one decode chunk + fan-out, nowhere near the ~900
        # tokens x 4 slots an un-interrupted drain would decode
        assert wall < 30.0
        for _, o in waiters:
            assert o["r"].stop_reason == "interrupt"
        ss = eng.serving_stats()
        assert ss["retained_kv_slots"] == 4
        assert ss["interrupts_total"] == 4
        assert eng.interrupts_by_reason == {"drain": 4}
        assert eng.n_pending_work == 0

        # token-exact resume of every drained sequence -> no retained leaks
        for i, (_, o) in enumerate(waiters):
            part = o["r"]
            cont = run_request(
                eng,
                f"d{i}",
                [10 + i, 20 + i, 3] + list(part.output_tokens),
                GenerationHyperparameters(max_new_tokens=4, greedy=True),
            )
            assert len(cont.output_tokens) == 4
        assert eng.serving_stats()["retained_kv_slots"] == 0
    finally:
        eng.stop()


def test_chaos_interrupt_fires_mid_commit(model, monkeypatch):
    """AREAL_CHAOS_INTERRUPT=mid-commit fires a deterministic interrupt
    right after a staged weight commit flips — the adversarial point where
    retained KV and the new version first coexist."""
    monkeypatch.setenv(chaos.INTERRUPT_CHAOS_ENV, "mid-commit")
    chaos.reset_interrupt_points()
    cfg, params = model
    eng = make_engine(model)
    try:
        done, out = submit_async(
            eng, "cc", [9, 8, 7],
            GenerationHyperparameters(max_new_tokens=400, greedy=True),
        )
        wait_tokens(eng, "cc")
        _staged_commit(
            eng, jax.tree.map(lambda x: x * 1.01, params), version=1
        )
        assert done.wait(30)
        part = out["r"]
        assert part.stop_reason == "interrupt"
        assert eng.interrupts_by_reason.get("chaos") == 1
        # pre-commit decode is all v0; the retained entry is tagged with
        # the freshly-committed version the resume will decode under
        assert part.output_versions == [0] * len(part.output_tokens)
        cont = run_request(
            eng,
            "cc",
            [9, 8, 7] + list(part.output_tokens),
            GenerationHyperparameters(max_new_tokens=6, greedy=True),
        )
        assert cont.output_versions == [1] * 6
        assert eng.serving_stats()["retained_kv_slots"] == 0
    finally:
        eng.stop()
        chaos.reset_interrupt_points()


def test_chaos_interrupt_mid_chunked_prefill(model, monkeypatch):
    """AREAL_CHAOS_INTERRUPT=mid-chunked-prefill cancels an intra-prompt
    warm between chunks: the partial KV is discarded (it must not straddle
    a commit) and the client gets a clean zero-token interrupt."""
    monkeypatch.setenv(chaos.INTERRUPT_CHAOS_ENV, "mid-chunked-prefill")
    chaos.reset_interrupt_points()
    eng = make_engine(model, chunked_prefill_tokens=32)
    try:
        long_prompt = list(np.arange(100) % 120)
        done, out = submit_async(
            eng, "warm", long_prompt,
            GenerationHyperparameters(max_new_tokens=8, greedy=True),
        )
        assert done.wait(60)
        r = out["r"]
        assert r.stop_reason == "interrupt"
        assert r.output_tokens == []
        assert eng.interrupts_by_reason.get("chaos") == 1
        ss = eng.serving_stats()
        assert ss["retained_kv_slots"] == 0  # warming KV is never retained
        # the engine stays healthy: the same prompt admits and finishes
        chaos.reset_interrupt_points()
        monkeypatch.delenv(chaos.INTERRUPT_CHAOS_ENV)
        r2 = run_request(
            eng, "warm", long_prompt,
            GenerationHyperparameters(max_new_tokens=8, greedy=True),
        )
        assert len(r2.output_tokens) == 8
    finally:
        eng.stop()
        chaos.reset_interrupt_points()
