"""Generic worker poll-loop framework (controller/worker_base.py) — the
reference's worker runtime capabilities (realhf/system/worker_base.py:
command server, status registry, group requests, heartbeat pulse) on
aiohttp + name_resolve."""

import json
import threading
import time

import numpy as np  # noqa: F401  (conftest platform setup)

from areal_tpu.controller.worker_base import (
    Worker,
    WorkerControl,
    WorkerStatus,
)
from areal_tpu.utils import name_resolve


class CountingWorker(Worker):
    def __init__(self, name, **kw):
        super().__init__(name, **kw)
        self.configured = None
        self.exited = False
        self.batch = 1

    def _configure(self, payload):
        self.configured = payload
        self.batch = int(payload.get("batch", 1))

    def _poll(self):
        time.sleep(0.001)
        return self.batch

    def _exit_hook(self):
        self.exited = True


class IdleWorker(Worker):
    def _poll(self):
        return 0


def _spawn(worker):
    t = threading.Thread(target=worker.run, daemon=True)
    t.start()
    deadline = time.time() + 10
    while worker._port is None and time.time() < deadline:
        time.sleep(0.01)
    assert worker._port is not None
    return t


def test_worker_lifecycle_and_group_requests():
    w1 = CountingWorker("trainer/0", record_root="/t/workers")
    w2 = CountingWorker("trainer/1", record_root="/t/workers")
    t1, t2 = _spawn(w1), _spawn(w2)
    panel = WorkerControl(record_root="/t/workers")

    recs = panel.worker_records()
    # panel keys are the names the workers were CONSTRUCTED with (ADVICE r4:
    # callers must not need to know the record-key '/'->'.' flattening)
    assert set(recs) == {"trainer/0", "trainer/1"}

    # addressing one worker by its constructed name works
    one = panel.group_request("configure", names=["trainer/0"])
    assert set(one) == {"trainer/0"}

    panel.group_request("configure")  # empty payload
    panel.group_request("start")
    panel.wait_all(WorkerStatus.RUNNING, timeout=10)
    time.sleep(0.2)
    assert w1._work_done > 0 and w2._work_done > 0

    panel.group_request("pause")
    done = w1._work_done
    time.sleep(0.1)
    assert w1._work_done == done  # paused: no progress
    assert w1.status == WorkerStatus.PAUSED

    panel.group_request("resume")
    time.sleep(0.1)
    assert w1._work_done > done

    panel.group_request("exit")
    t1.join(timeout=10)
    t2.join(timeout=10)
    assert not t1.is_alive() and not t2.is_alive()
    assert w1.exited and w2.exited


def test_idle_backoff_and_status_endpoint():
    w = IdleWorker("idle/0", record_root="/t2/workers")
    t = _spawn(w)
    panel = WorkerControl(record_root="/t2/workers")
    panel.group_request("start")
    time.sleep(0.3)
    # idle worker backs off instead of hot-spinning: far fewer rounds than
    # a 1ms-tight loop would give
    assert w._poll_rounds < 200
    st = panel.get_status(next(iter(panel.worker_records())))
    assert st == WorkerStatus.RUNNING
    panel.group_request("exit")
    t.join(timeout=10)


def test_pulse_marks_stale_heartbeat_lost():
    w = CountingWorker("hb/0", record_root="/t3/workers")
    t = _spawn(w)
    panel = WorkerControl(record_root="/t3/workers", heartbeat_timeout=0.2)
    assert panel.pulse()[next(iter(panel.worker_records()))] in (
        WorkerStatus.STANDBY,
        WorkerStatus.RUNNING,
    )
    # forge a stale beat (a dead process stops re-announcing)
    key = next(
        k for k in name_resolve.find_subtree("/t3/workers")
    )
    rec = json.loads(name_resolve.get(key))
    rec["beat"] = time.time() - 60
    name_resolve.add(key, json.dumps(rec), replace=True)
    w._last_beat = time.time()  # stop the worker refreshing during check
    statuses = panel.pulse()
    assert list(statuses.values())[0] == WorkerStatus.LOST
    panel.group_request("exit")
    t.join(timeout=10)


def test_configure_payload_reaches_worker():
    w = CountingWorker("cfg/0", record_root="/t4/workers")
    t = _spawn(w)
    panel = WorkerControl(record_root="/t4/workers")
    recs = panel.worker_records()
    addr = list(recs.values())[0]["addr"]
    import urllib.request

    req = urllib.request.Request(
        f"http://{addr}/cmd/configure",
        data=json.dumps({"batch": 5}).encode(),
        method="POST",
    )
    urllib.request.urlopen(req, timeout=10).read()
    assert w.configured == {"batch": 5}
    assert w.batch == 5
    panel.group_request("exit")
    t.join(timeout=10)
