"""Name-resolve backend tests (modeled on the reference's parametrized
realhf/tests/distributed/test_name_resolve.py)."""

import threading
import time

import pytest

from areal_tpu.utils.name_resolve import (
    MemoryNameRecordRepository,
    NameEntryExistsError,
    NameEntryNotFoundError,
    NfsNameRecordRepository,
    TimeoutError_,
)


@pytest.fixture(params=["memory", "nfs"])
def repo(request, tmp_path):
    if request.param == "memory":
        return MemoryNameRecordRepository()
    return NfsNameRecordRepository(str(tmp_path / "nr"))


def test_add_get_delete(repo):
    repo.add("a/b/c", "v1")
    assert repo.get("a/b/c") == "v1"
    with pytest.raises(NameEntryExistsError):
        repo.add("a/b/c", "v2")
    repo.add("a/b/c", "v2", replace=True)
    assert repo.get("a/b/c") == "v2"
    repo.delete("a/b/c")
    with pytest.raises(NameEntryNotFoundError):
        repo.get("a/b/c")
    with pytest.raises(NameEntryNotFoundError):
        repo.delete("a/b/c")


def test_subtree(repo):
    repo.add("root/x/1", "v1")
    repo.add("root/x/2", "v2")
    repo.add("root/y/3", "v3")
    assert repo.get_subtree("root/x") == ["v1", "v2"]
    assert repo.find_subtree("root/x") == ["root/x/1", "root/x/2"]
    repo.clear_subtree("root")
    assert repo.get_subtree("root") == []


def test_add_subentry(repo):
    n1 = repo.add_subentry("servers", "addr1")
    n2 = repo.add_subentry("servers", "addr2")
    assert n1 != n2
    assert sorted(repo.get_subtree("servers")) == ["addr1", "addr2"]


def test_wait_timeout(repo):
    with pytest.raises(TimeoutError_):
        repo.wait("nope", timeout=0.2, poll_frequency=0.05)


def test_wait_concurrent(repo):
    def writer():
        time.sleep(0.2)
        repo.add("late/key", "yes")

    t = threading.Thread(target=writer)
    t.start()
    assert repo.wait("late/key", timeout=30) == "yes"
    t.join()


def test_nfs_exclusive_create_atomic(tmp_path):
    """replace=False must be a single atomic op (DistributedLock acquire)."""
    from areal_tpu.utils.name_resolve import (
        NameEntryExistsError,
        NfsNameRecordRepository,
    )

    repo = NfsNameRecordRepository(str(tmp_path))
    repo.add("lk", "a", replace=False)
    with pytest.raises(NameEntryExistsError):
        repo.add("lk", "b", replace=False)
    assert repo.get("lk") == "a"


def test_distributed_lock_mutual_exclusion(tmp_path):
    import threading

    from areal_tpu.utils import name_resolve
    from areal_tpu.utils.lock import DistributedLock
    from areal_tpu.utils.name_resolve import NameResolveConfig

    name_resolve.reconfigure(
        NameResolveConfig(type="nfs", nfs_record_root=str(tmp_path))
    )
    counter = {"v": 0, "max_in": 0, "in": 0}
    lk_lock = threading.Lock()

    def work(i):
        lock = DistributedLock("crit", ttl=30)
        with lock:
            with lk_lock:
                counter["in"] += 1
                counter["max_in"] = max(counter["max_in"], counter["in"])
            counter["v"] += 1
            with lk_lock:
                counter["in"] -= 1

    threads = [threading.Thread(target=work, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert counter["v"] == 6
    assert counter["max_in"] == 1  # never two holders at once


def test_distributed_lock_breaks_expired(tmp_path):
    from areal_tpu.utils import name_resolve
    from areal_tpu.utils.lock import DistributedLock
    from areal_tpu.utils.name_resolve import NameResolveConfig

    name_resolve.reconfigure(
        NameResolveConfig(type="nfs", nfs_record_root=str(tmp_path))
    )
    dead = DistributedLock("stale", ttl=0.1)
    assert dead.acquire(timeout=1)
    # owner "crashes" (no release); a new holder breaks the expired lock
    import time as _t

    _t.sleep(0.2)
    fresh = DistributedLock("stale", ttl=0.1)
    assert fresh.acquire(timeout=5)
    fresh.release()


def test_etcd_backend_gated():
    """Real etcd only: skip unless one is reachable."""
    import urllib.request

    from areal_tpu.utils.name_resolve import EtcdNameRecordRepository

    repo = EtcdNameRecordRepository("127.0.0.1:2379")
    try:
        repo.add("areal-test/x", "1", replace=True)
    except Exception:
        pytest.skip("no etcd at 127.0.0.1:2379")
    assert repo.get("areal-test/x") == "1"
    repo.clear_subtree("areal-test")
