"""Name-resolve backend tests (modeled on the reference's parametrized
realhf/tests/distributed/test_name_resolve.py)."""

import threading
import time

import pytest

from areal_tpu.utils.name_resolve import (
    MemoryNameRecordRepository,
    NameEntryExistsError,
    NameEntryNotFoundError,
    NfsNameRecordRepository,
    TimeoutError_,
)


@pytest.fixture(params=["memory", "nfs"])
def repo(request, tmp_path):
    if request.param == "memory":
        return MemoryNameRecordRepository()
    return NfsNameRecordRepository(str(tmp_path / "nr"))


def test_add_get_delete(repo):
    repo.add("a/b/c", "v1")
    assert repo.get("a/b/c") == "v1"
    with pytest.raises(NameEntryExistsError):
        repo.add("a/b/c", "v2")
    repo.add("a/b/c", "v2", replace=True)
    assert repo.get("a/b/c") == "v2"
    repo.delete("a/b/c")
    with pytest.raises(NameEntryNotFoundError):
        repo.get("a/b/c")
    with pytest.raises(NameEntryNotFoundError):
        repo.delete("a/b/c")


def test_subtree(repo):
    repo.add("root/x/1", "v1")
    repo.add("root/x/2", "v2")
    repo.add("root/y/3", "v3")
    assert repo.get_subtree("root/x") == ["v1", "v2"]
    assert repo.find_subtree("root/x") == ["root/x/1", "root/x/2"]
    repo.clear_subtree("root")
    assert repo.get_subtree("root") == []


def test_add_subentry(repo):
    n1 = repo.add_subentry("servers", "addr1")
    n2 = repo.add_subentry("servers", "addr2")
    assert n1 != n2
    assert sorted(repo.get_subtree("servers")) == ["addr1", "addr2"]


def test_wait_timeout(repo):
    with pytest.raises(TimeoutError_):
        repo.wait("nope", timeout=0.2, poll_frequency=0.05)


def test_wait_concurrent(repo):
    def writer():
        time.sleep(0.2)
        repo.add("late/key", "yes")

    t = threading.Thread(target=writer)
    t.start()
    assert repo.wait("late/key", timeout=5) == "yes"
    t.join()
