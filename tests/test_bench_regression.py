"""Perf-regression sentinel fixture suite (areal_tpu/bench/regression.py):
synthetic regression detected, noise-band pass, first-run/no-baseline
pass, wedged-rung skip, direction inference, verdict append, CLI gate."""

import importlib.util
import json
import subprocess
import sys
import os

import pytest

from areal_tpu.bench import regression as reg

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _recs(metric, values, unit="tokens/s"):
    return [{"metric": metric, "value": v, "unit": unit} for v in values]


def _write_jsonl(path, records):
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")


def test_synthetic_20pct_regression_detected():
    r = reg.analyze(_recs("decode_tokens_per_sec", [100, 102, 98, 101, 80]))
    v = r["metrics"]["decode_tokens_per_sec"]
    assert v["status"] == "regression"
    assert not r["ok"]
    assert r["regressions"] == ["decode_tokens_per_sec"]


def test_noise_band_jitter_passes():
    r = reg.analyze(_recs("decode_tokens_per_sec", [100, 102, 98, 101, 97]))
    assert r["ok"]
    assert r["metrics"]["decode_tokens_per_sec"]["status"] == "ok"


def test_first_run_no_baseline_passes():
    r = reg.analyze(_recs("decode_tokens_per_sec", [100]))
    assert r["ok"]
    assert (
        r["metrics"]["decode_tokens_per_sec"]["status"] == "no_baseline"
    )
    # two samples: still below min_samples=2 baseline (1 trailing)
    r = reg.analyze(_recs("decode_tokens_per_sec", [100, 50]))
    assert r["ok"]


def test_wedged_rung_is_no_data_never_regression_or_baseline():
    recs = _recs("decode_tokens_per_sec", [100, 101, 99])
    # wedged record inside the history: excluded from the baseline
    recs.insert(
        1,
        {"metric": "decode_tokens_per_sec", "value": None, "wedged": True,
         "phase": "backend_probe", "timeout_s": 6000},
    )
    # wedged NEWEST: no data, not a regression (rc=124 forensics)
    recs.append(
        {"metric": "decode_tokens_per_sec", "value": None, "wedged": True,
         "phase": "decode", "timeout_s": 900},
    )
    r = reg.analyze(recs)
    v = r["metrics"]["decode_tokens_per_sec"]
    assert r["ok"] and v["status"] == "no_data"
    assert v["wedged"] and v["phase"] == "decode"


def test_lower_is_better_direction():
    # a stall that GREW 50% is a regression
    r = reg.analyze(
        _recs("weight_sync_stall_seconds", [0.02, 0.021, 0.019, 0.03],
              unit="s")
    )
    assert not r["ok"]
    # a stall that SHRANK is an improvement, not a regression
    r = reg.analyze(
        _recs("weight_sync_stall_seconds", [0.02, 0.021, 0.019, 0.002],
              unit="s")
    )
    assert r["ok"]
    assert (
        r["metrics"]["weight_sync_stall_seconds"]["status"] == "improvement"
    )


def test_direction_inference_table():
    assert not reg.lower_is_better("decode_tokens_per_sec")
    assert not reg.lower_is_better("sft_train_tokens_per_sec_per_chip_x")
    assert not reg.lower_is_better("prefix_cache_prefill_reduction")
    assert not reg.lower_is_better("pallas_kernel_validation")
    assert reg.lower_is_better("grpo_step_sec")
    assert reg.lower_is_better("weight_update_latency", "s_shm")
    assert reg.lower_is_better("weight_sync_stall_seconds", "s")
    assert reg.lower_is_better("anything", "s")


def test_improvement_and_mad_band():
    # tight history: MAD ~ 1, band = max(3*1.4826*1, 0.1*100) = 10
    r = reg.analyze(_recs("m_per_sec", [100, 101, 99, 100, 112]))
    assert r["metrics"]["m_per_sec"]["status"] == "improvement"
    r = reg.analyze(_recs("m_per_sec", [100, 101, 99, 100, 109]))
    assert r["metrics"]["m_per_sec"]["status"] == "ok"


def test_run_grouping_duplicates_collapse_and_absent_rung_is_no_data():
    """Run-aware analysis: duplicate emissions within one run collapse
    (last wins, never polluting that run's own baseline), and a metric
    with NO sample in the newest run — a rung that crashed without even
    a timeout — is no_data, not silently judged on the previous run's
    stale value."""
    recs = [
        {"metric": "a_per_sec", "value": 100, "run_id": "r1"},
        {"metric": "b_per_sec", "value": 50, "run_id": "r1"},
        {"metric": "a_per_sec", "value": 101, "run_id": "r2"},
        # duplicate within r2: collapses to the later 99
        {"metric": "a_per_sec", "value": 42, "run_id": "r2"},
        {"metric": "a_per_sec", "value": 99, "run_id": "r2"},
        {"metric": "a_per_sec", "value": 100, "run_id": "r3"},
        # b_per_sec emitted NOTHING in r2/r3
    ]
    r = reg.analyze(recs)
    assert r["ok"]
    a = r["metrics"]["a_per_sec"]
    # baseline = one sample per prior run ([100, 99]) — the 42/101
    # duplicates collapsed; 2 samples reach min_samples
    assert a["status"] == "ok" and a["n_baseline"] == 2
    b = r["metrics"]["b_per_sec"]
    assert b["status"] == "no_data"
    assert b["absent_from_run"] == "r3"
    assert b["last_seen_run"] == "r1"


def test_legacy_lines_without_run_id_each_stand_alone():
    """Pre-run_id trajectory lines (PR 7/8 appends) each count as their
    own run sample, so the existing history still baselines."""
    recs = _recs("m_per_sec", [100, 101, 99, 100])  # no run_id anywhere
    recs.append({"metric": "m_per_sec", "value": 70, "run_id": "r9"})
    r = reg.analyze(recs)
    assert r["metrics"]["m_per_sec"]["status"] == "regression"
    assert r["metrics"]["m_per_sec"]["n_baseline"] == 4


def test_sentinel_verdict_lines_are_not_data(tmp_path):
    path = str(tmp_path / "t.jsonl")
    _write_jsonl(path, _recs("m_per_sec", [100, 101, 99, 100]))
    report = reg.analyze_file(path)
    reg.append_verdict(path, report, run_id="r1")
    # re-analysis sees the same 4 data records, not 5
    again = reg.analyze_file(path)
    assert again["n_records"] == 4
    last = json.loads(open(path).read().strip().splitlines()[-1])
    assert last["metric"] == reg.SENTINEL_METRIC
    assert last["run_id"] == "r1"
    assert last["verdicts"]["m_per_sec"] == "ok"


def test_garbled_lines_skipped(tmp_path):
    path = str(tmp_path / "t.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"metric": "m", "value": 1.0}) + "\n")
        f.write("{torn tail\n")
        f.write("not json at all\n")
    assert len(reg.load_records(path)) == 1


def test_self_test_passes():
    assert reg.self_test() == 0


def test_cli_gates_regression(tmp_path):
    path = str(tmp_path / "t.jsonl")
    _write_jsonl(path, _recs("m_per_sec", [100, 101, 99, 100, 70]))
    assert reg.main(["--jsonl", path]) == 1
    _write_jsonl(path, _recs("m_per_sec", [100, 101, 99, 100, 99]))
    assert reg.main(["--jsonl", path]) == 0
    # missing trajectory: nothing to gate, pass
    assert reg.main(["--jsonl", str(tmp_path / "missing.jsonl")]) == 0


def test_bench_check_script_self_test():
    r = subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "bench_check.sh"),
         "--self-test"],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert r.returncode == 0, r.stderr


def test_bench_parent_loads_sentinel_without_jax(tmp_path):
    """bench.py's by-path loader keeps the no-jax-in-parent invariant and
    appends a verdict line after a rehearsal run (pinned without running
    the full ladder: drive the append helper in a fresh interpreter)."""
    traj = str(tmp_path / "traj.jsonl")
    _write_jsonl(traj, _recs("m_per_sec", [100, 99, 101, 100]))
    code = f"""
import importlib.util, json, sys
sys.argv = ["bench.py"]
spec = importlib.util.spec_from_file_location("benchmod", {json.dumps(os.path.join(REPO, "bench.py"))})
m = importlib.util.module_from_spec(spec); sys.modules["benchmod"] = m
spec.loader.exec_module(m)
assert "jax" not in sys.modules, "bench parent imported jax"
report = m.append_rehearsal_verdict({json.dumps(traj)})
assert report is not None and report["ok"], report
assert "jax" not in sys.modules, "sentinel pulled jax into the parent"
print("OK")
"""
    r = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        cwd=REPO, timeout=120,
    )
    assert r.returncode == 0, r.stderr
    assert "OK" in r.stdout
    last = json.loads(open(traj).read().strip().splitlines()[-1])
    assert last["metric"] == reg.SENTINEL_METRIC


def test_bench_emit_wedged_shape(tmp_path, monkeypatch):
    """The wedge-forensics record bench.py writes on a child timeout has
    the exact shape the sentinel skips."""
    spec = importlib.util.spec_from_file_location(
        "benchmod2", os.path.join(REPO, "bench.py")
    )
    m = importlib.util.module_from_spec(spec)
    sys.modules["benchmod2"] = m
    monkeypatch.setattr(sys, "argv", ["bench.py"])
    spec.loader.exec_module(m)
    monkeypatch.setattr(m, "PARTIAL_PATH", str(tmp_path / "p.jsonl"))
    m.emit_wedged("decode_tokens_per_sec", "decode", 900.0)
    rec = json.loads(open(tmp_path / "p.jsonl").read())
    assert rec["wedged"] is True
    assert rec["phase"] == "decode"
    assert rec["timeout_s"] == 900.0
    assert rec["value"] is None
    assert "run_id" in rec
    r = reg.analyze([rec])
    assert r["metrics"]["decode_tokens_per_sec"]["status"] == "no_data"


def test_band_floor_override_covers_bimodal_rung(tmp_path):
    """A metric in BAND_FLOOR_OVERRIDES uses its own relative floor: a
    swing inside the widened band (the rung's other mode) is ok, while a
    collapse past it still gates."""
    from areal_tpu.bench import regression as R

    assert "elastic_fleet" in R.BAND_FLOOR_OVERRIDES
    lines = [
        {"metric": "elastic_fleet", "value": v, "unit": "x", "run_id": f"r{i}",
         "ts": float(i)}
        for i, v in enumerate([6.1, 6.0, 5.2, 6.2])
    ]
    lines.append({"metric": "elastic_fleet", "value": 5.25, "unit": "x",
                  "run_id": "r9", "ts": 9.0})
    p = tmp_path / "t.jsonl"
    p.write_text("\n".join(json.dumps(x) for x in lines) + "\n")
    rep = R.analyze_file(str(p), R.BenchSentinelConfig())
    assert rep["metrics"]["elastic_fleet"]["status"] == "ok"
    # a genuine collapse (autoscale not engaging) still gates
    lines[-1]["value"] = 1.1
    p.write_text("\n".join(json.dumps(x) for x in lines) + "\n")
    rep = R.analyze_file(str(p), R.BenchSentinelConfig())
    assert rep["metrics"]["elastic_fleet"]["status"] == "regression"
