"""Ragged paged-attention Pallas decode kernel (ops/pallas/paged_attention)
vs the XLA gather path — interpret mode on CPU, so the kernel tier is
tier-1-testable, plus the e2e greedy-identity bar `use_pallas_decode` must
clear (same bar PR 5/6 used for weight-sync / prefix-cache invisibility).
Includes the int8 composition: `kv_quant="int8"` + `use_pallas_decode` runs
the kernel with in-kernel dequant (parity vs the XLA dequant-gather path,
token-identical greedy e2e), and only tp>1 still falls back — loudly."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from areal_tpu.api.cli_args import GenerationHyperparameters, JaxGenConfig
from areal_tpu.inference.engine import GenerationEngine
from areal_tpu.models.config import tiny_config
from areal_tpu.models.lm import init_params, quantize_kv_rows
from areal_tpu.ops.attention import AttnSpec, decode_attention_xla
from areal_tpu.ops.pallas.paged_attention import paged_decode_attention


def _rand(rng, shape):
    return jnp.asarray(rng.normal(size=shape), jnp.float32)


def _ref(q, k_pool, v_pool, tbl, lens, window=0):
    b, nbt = tbl.shape
    bs = k_pool.shape[1]
    view_k = k_pool[tbl].reshape(b, nbt * bs, *k_pool.shape[2:])
    view_v = v_pool[tbl].reshape(b, nbt * bs, *v_pool.shape[2:])
    return decode_attention_xla(q, view_k, view_v, lens, window=window)


def _check(q, k_pool, v_pool, tbl, lens, window=0, **tol):
    out = paged_decode_attention(
        q, k_pool, v_pool, tbl, lens, window=window, interpret=True
    )
    ref = _ref(q, k_pool, v_pool, tbl, lens, window=window)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref),
        rtol=tol.get("rtol", 1e-5), atol=tol.get("atol", 1e-5),
    )


def test_parity_ragged_lengths_gqa():
    """Mixed-depth slots incl. len=1 (fresh decode), exact block multiple,
    and mid-block lengths; GQA group 2."""
    rng = np.random.default_rng(0)
    B, NH, KH, D, NB, BS, NBT = 4, 4, 2, 32, 32, 8, 6
    q = _rand(rng, (B, 1, NH, D))
    kp, vp = _rand(rng, (NB, BS, KH, D)), _rand(rng, (NB, BS, KH, D))
    tbl = jnp.asarray(
        rng.permutation(NB)[: B * NBT].reshape(B, NBT).astype(np.int32)
    )
    lens = jnp.asarray([1, 8, 13, 48], jnp.int32)
    _check(q, kp, vp, tbl, lens)


def test_parity_per_query_causal_tq_gt_1():
    """Tq > 1 (chunked-prefill tail / spec-verify shape): query row t sees
    cache positions <= cache_len + t — per-query causal masking."""
    rng = np.random.default_rng(1)
    B, Tq, NH, KH, D, NB, BS, NBT = 3, 4, 4, 2, 32, 32, 8, 6
    q = _rand(rng, (B, Tq, NH, D))
    kp, vp = _rand(rng, (NB, BS, KH, D)), _rand(rng, (NB, BS, KH, D))
    tbl = jnp.asarray(
        rng.permutation(NB)[: B * NBT].reshape(B, NBT).astype(np.int32)
    )
    lens = jnp.asarray([4, 11, 37], jnp.int32)  # total incl. the Tq rows
    _check(q, kp, vp, tbl, lens)


def test_parity_sliding_window():
    rng = np.random.default_rng(2)
    B, Tq, NH, KH, D, NB, BS, NBT = 2, 2, 4, 4, 32, 16, 8, 4
    q = _rand(rng, (B, Tq, NH, D))
    kp, vp = _rand(rng, (NB, BS, KH, D)), _rand(rng, (NB, BS, KH, D))
    tbl = jnp.asarray(
        rng.permutation(NB)[: B * NBT].reshape(B, NBT).astype(np.int32)
    )
    lens = jnp.asarray([9, 27], jnp.int32)
    _check(q, kp, vp, tbl, lens, window=5)


def test_parity_holes_and_recycled_blocks():
    """Block tables with holes (trash-clamped unmapped tails, id 0) and
    RECYCLED physical blocks (two slots sharing a block id, and a block id
    reused at different logical positions) — exactly what a churned
    BlockPool + radix cache hands the kernel."""
    rng = np.random.default_rng(3)
    B, NH, KH, D, NB, BS, NBT = 3, 4, 2, 32, 8, 8, 4
    q = _rand(rng, (B, 1, NH, D))
    kp, vp = _rand(rng, (NB, BS, KH, D)), _rand(rng, (NB, BS, KH, D))
    tbl = np.zeros((B, NBT), np.int32)  # unmapped tail = trash block 0
    tbl[0, :2] = [3, 5]
    tbl[1, :3] = [5, 3, 7]  # blocks 3 and 5 shared with slot 0, reordered
    tbl[2, :1] = [7]
    lens = jnp.asarray([14, 20, 3], jnp.int32)
    _check(q, kp, vp, jnp.asarray(tbl), lens)


def test_parity_prefix_cache_hit_mid_block():
    """Prefix-cache-hit decode: cache_len > 0 lands mid-block (the radix
    admit covered part of the prompt; the first fresh token writes at a
    mid-block offset) — the kernel must mask the block's stale tail."""
    rng = np.random.default_rng(4)
    B, NH, KH, D, NB, BS, NBT = 2, 4, 2, 32, 16, 8, 4
    kp, vp = _rand(rng, (NB, BS, KH, D)), _rand(rng, (NB, BS, KH, D))
    tbl = jnp.asarray(
        rng.permutation(NB)[: B * NBT].reshape(B, NBT).astype(np.int32)
    )
    # slot 0: cache covered 12 tokens (block 1 half full) + 1 new = 13;
    # slot 1: covered 5 + 1 new = 6 (first block still filling)
    q = _rand(rng, (B, 1, NH, D))
    lens = jnp.asarray([13, 6], jnp.int32)
    _check(q, kp, vp, tbl, lens)


def test_parity_int8_quantized_pool():
    """int8 pools: the kernel dequantizes rows through the per-(row, head)
    scale planes IN-KERNEL; reference is the XLA dequant-gather path
    (_pool_view semantics: (int8.f32 * scale).astype(q.dtype))."""
    rng = np.random.default_rng(6)
    B, Tq, NH, KH, D, NB, BS, NBT = 3, 2, 4, 2, 32, 32, 8, 6
    q = _rand(rng, (B, Tq, NH, D))
    kp, vp = _rand(rng, (NB, BS, KH, D)), _rand(rng, (NB, BS, KH, D))
    kq, ks = quantize_kv_rows(kp)
    vq, vs = quantize_kv_rows(vp)
    tbl = jnp.asarray(
        rng.permutation(NB)[: B * NBT].reshape(B, NBT).astype(np.int32)
    )
    lens = jnp.asarray([2, 13, 48], jnp.int32)
    out = paged_decode_attention(
        q, kq, vq, tbl, lens, interpret=True, k_scale=ks, v_scale=vs
    )
    kd = (kq.astype(jnp.float32) * ks[..., None]).astype(q.dtype)
    vd = (vq.astype(jnp.float32) * vs[..., None]).astype(q.dtype)
    ref = _ref(q, kd, vd, tbl, lens)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5
    )


def test_parity_int8_sliding_window():
    rng = np.random.default_rng(7)
    B, NH, KH, D, NB, BS, NBT = 2, 4, 4, 32, 16, 8, 4
    q = _rand(rng, (B, 1, NH, D))
    kp, vp = _rand(rng, (NB, BS, KH, D)), _rand(rng, (NB, BS, KH, D))
    kq, ks = quantize_kv_rows(kp)
    vq, vs = quantize_kv_rows(vp)
    tbl = jnp.asarray(
        rng.permutation(NB)[: B * NBT].reshape(B, NBT).astype(np.int32)
    )
    lens = jnp.asarray([9, 27], jnp.int32)
    out = paged_decode_attention(
        q, kq, vq, tbl, lens, window=5, interpret=True,
        k_scale=ks, v_scale=vs,
    )
    kd = (kq.astype(jnp.float32) * ks[..., None]).astype(q.dtype)
    vd = (vq.astype(jnp.float32) * vs[..., None]).astype(q.dtype)
    ref = _ref(q, kd, vd, tbl, lens, window=5)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5
    )


def test_parity_under_jit_and_bf16():
    rng = np.random.default_rng(5)
    B, NH, KH, D, NB, BS, NBT = 2, 2, 2, 32, 16, 8, 4
    q = _rand(rng, (B, 1, NH, D)).astype(jnp.bfloat16)
    kp = _rand(rng, (NB, BS, KH, D)).astype(jnp.bfloat16)
    vp = _rand(rng, (NB, BS, KH, D)).astype(jnp.bfloat16)
    tbl = jnp.asarray(
        rng.permutation(NB)[: B * NBT].reshape(B, NBT).astype(np.int32)
    )
    lens = jnp.asarray([7, 22], jnp.int32)
    out = jax.jit(
        lambda *a: paged_decode_attention(*a, interpret=True)
    )(q, kp, vp, tbl, lens)
    ref = _ref(q, kp, vp, tbl, lens)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        rtol=2e-2, atol=2e-2,
    )


# ---------------------------------------------------------------------------
# e2e: the engine knob
# ---------------------------------------------------------------------------


def _engine(use_pallas, **kw):
    cfg = tiny_config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
    )
    params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    defaults = dict(
        max_batch_size=4, max_seq_len=128, prefill_chunk=64,
        decode_steps_per_call=4, page_size=16, dtype="float32",
        use_pallas_decode=use_pallas,
    )
    defaults.update(kw)
    return GenerationEngine(
        JaxGenConfig(**defaults), model_config=cfg, params=params
    )


def _generate(eng, prompts, max_new=8):
    results: list = []
    for i, p in enumerate(prompts):
        eng.submit(
            f"r{i}", p,
            GenerationHyperparameters(max_new_tokens=max_new, greedy=True),
            lambda r, i=i: results.append((i, r)),
        )
    it = 0
    while len(results) < len(prompts):
        eng._handle_aborts()
        eng._admit()
        if eng.n_running:
            eng._decode_chunk()
        it += 1
        assert it < 500, "engine made no progress"
    return {i: r for i, r in results}


def test_e2e_greedy_identity_pallas_decode_on_vs_off():
    """The acceptance bar: greedy outputs token-identical with
    use_pallas_decode on vs off, and logprobs numerically close."""
    prompts = [[5, 9, 3, 7, 2, 6], [11, 4, 8, 1], [9, 9, 2, 4, 4]]
    off = _generate(_engine(False), prompts)
    eng = _engine(True)
    assert eng.attn_spec.decode_impl == "pallas_interpret"
    on = _generate(eng, prompts)
    for i in range(len(prompts)):
        assert off[i].output_tokens == on[i].output_tokens, i
        np.testing.assert_allclose(
            off[i].output_logprobs, on[i].output_logprobs,
            rtol=1e-4, atol=1e-5,
        )


def test_e2e_greedy_identity_int8_pallas_on_vs_off():
    """The ISSUE 16 acceptance bar: kv_quant="int8" + use_pallas_decode
    runs the kernel (no fallback) and greedy outputs are token-identical
    kernel-on vs kernel-off over the SAME quantized pools."""
    prompts = [[5, 9, 3, 7, 2, 6], [11, 4, 8, 1], [9, 9, 2, 4, 4]]
    off = _generate(_engine(False, kv_quant="int8"), prompts)
    eng = _engine(True, kv_quant="int8")
    assert eng.attn_spec.decode_impl == "pallas_interpret"
    assert eng.metrics_snapshot()["pallas_fallback_total"] == 0
    on = _generate(eng, prompts)
    for i in range(len(prompts)):
        assert off[i].output_tokens == on[i].output_tokens, i
        np.testing.assert_allclose(
            off[i].output_logprobs, on[i].output_logprobs,
            rtol=1e-4, atol=1e-5,
        )


def test_knob_falls_back_loudly_on_unsupported_configs(caplog):
    """int8 pools now COMPOSE with the kernel (in-kernel dequant); only
    tp>1 keeps the XLA path — with a one-shot warning and a counted
    pallas_fallback_total{site,reason} entry, never a silently different
    kernel."""
    eng = _engine(True, kv_quant="int8")
    assert eng.attn_spec.decode_impl == "pallas_interpret"
    assert eng.metrics_snapshot()["pallas_fallback_total"] == 0
    eng2 = _engine(True, tp_size=2)
    assert eng2.attn_spec.decode_impl == "xla"
    snap = eng2.metrics_snapshot()
    assert snap["pallas_fallback_total"] == 1
    assert snap["pallas_fallback_total{site=decode,reason=tp_size}"] == 1


def test_kv_pool_bytes_gauge_reflects_quantization():
    """serving_stats reports the pool's byte footprint split into row
    storage and scale overhead: the int8 memory win is a scrapeable
    number (int8 rows = 1/4 the f32 rows; scales nonzero only there)."""
    fp = _engine(False).serving_stats()
    q8 = _engine(False, kv_quant="int8").serving_stats()
    assert fp["kv_pool_dtype"] == "float32" and fp["kv_pool_scale_bytes"] == 0
    assert fp["kv_pool_bytes"] == fp["kv_pool_kv_bytes"]
    assert q8["kv_pool_dtype"] == "int8" and q8["kv_pool_scale_bytes"] > 0
    assert q8["kv_pool_kv_bytes"] * 4 == fp["kv_pool_kv_bytes"]
    assert q8["kv_pool_bytes"] == (
        q8["kv_pool_kv_bytes"] + q8["kv_pool_scale_bytes"]
    )
    # the headline: quantized pool + scale overhead still well under fp
    assert q8["kv_pool_bytes"] < fp["kv_pool_bytes"]


def test_quantized_pool_layer_runs_kernel_path():
    """_decode_paged_layer routes int8 pools THROUGH the kernel when the
    spec asks for it, and the result matches the XLA dequant-gather path
    on the same pools (the dispatch-level parity check under real layer
    weights)."""
    from areal_tpu.models.lm import _decode_paged_layer

    cfg = tiny_config(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
    )
    params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    lp = jax.tree.map(lambda a: a[0], params["layers"])
    rng = np.random.default_rng(8)
    B, NB, BS, NBT, D = 2, 8, 8, 2, cfg.head_dim
    rows_k = _rand(rng, (NB, BS, 2, D))
    rows_v = _rand(rng, (NB, BS, 2, D))
    kq, ks = quantize_kv_rows(rows_k)
    vq, vs = quantize_kv_rows(rows_v)
    pool = {"k": kq, "ks": ks, "v": vq, "vs": vs}
    h = jnp.asarray(
        rng.normal(size=(B, 1, cfg.hidden_size)), jnp.float32
    )
    rope = jnp.zeros((B, 1), jnp.int32)
    tbl = jnp.asarray([[1, 2], [3, 4]], jnp.int32)
    lens = jnp.asarray([5, 11], jnp.int32)
    args = (
        cfg, lp, dict(pool), h, rope,
        jnp.zeros((B,), jnp.int32), jnp.zeros((B,), jnp.int32),
        tbl, lens,
    )
    out_kern, _ = _decode_paged_layer(
        *args, AttnSpec(decode_impl="pallas_interpret")
    )
    out_xla, _ = _decode_paged_layer(*args, AttnSpec(decode_impl="xla"))
    np.testing.assert_allclose(
        np.asarray(out_kern), np.asarray(out_xla), rtol=1e-5, atol=1e-5
    )
