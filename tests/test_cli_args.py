import pytest

from areal_tpu.api.cli_args import (
    GRPOConfig,
    SFTConfig,
    from_dict,
    load_expr_config,
    parse_cli_args,
)


def test_from_dict_nested_coercion():
    cfg = from_dict(
        GRPOConfig,
        {
            "experiment_name": "e",
            "trial_name": "t",
            "actor": {
                "optimizer": {"lr": "1e-4"},
                "eps_clip": "0.3",
                "ppo_n_minibatches": "2",
            },
            "gconfig": {"max_new_tokens": 128, "temperature": 1},
        },
    )
    assert cfg.actor.eps_clip == 0.3
    assert cfg.actor.ppo_n_minibatches == 2
    assert cfg.actor.optimizer.lr == 1e-4
    assert cfg.gconfig.temperature == 1.0
    assert isinstance(cfg.gconfig.temperature, float)


def test_from_dict_unknown_key_raises():
    with pytest.raises(ValueError, match="Unknown config keys"):
        from_dict(SFTConfig, {"not_a_key": 1})


def test_yaml_plus_overrides(tmp_path):
    p = tmp_path / "c.yaml"
    p.write_text(
        "experiment_name: exp\ntrial_name: t0\nactor:\n  eps_clip: 0.1\n"
    )
    data, _ = parse_cli_args(
        ["--config", str(p), "actor.eps_clip=0.25", "seed=7", "async_training=false"]
    )
    cfg = from_dict(GRPOConfig, data)
    assert cfg.actor.eps_clip == 0.25
    assert cfg.seed == 7
    assert cfg.async_training is False


def test_load_expr_config(tmp_path):
    p = tmp_path / "c.yaml"
    p.write_text("experiment_name: exp\ntrial_name: t0\n")
    cfg, path = load_expr_config(["--config", str(p)], SFTConfig)
    assert cfg.experiment_name == "exp"
    assert path == str(p)
    # experiment/trial names propagate into sub-configs
    assert cfg.saver.experiment_name == "exp"
    assert cfg.stats_logger.trial_name == "t0"


def test_override_without_config_file():
    data, _ = parse_cli_args(["total_train_epochs=3"])
    cfg = from_dict(SFTConfig, data)
    assert cfg.total_train_epochs == 3


def test_bad_override():
    with pytest.raises(ValueError):
        parse_cli_args(["keynovalue"])


def test_cli_docs_generator_covers_all_configs():
    """docs/generate_cli_docs.py emits a section per config dataclass."""
    import dataclasses
    import io
    import importlib.util
    import os

    from areal_tpu.api import cli_args

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "gen_cli_docs", os.path.join(repo, "docs", "generate_cli_docs.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    buf = io.StringIO()
    mod.main(out=buf)
    text = buf.getvalue()
    # the committed reference must match the generator (no hand edits /
    # no stale docs after a cli_args change)
    with open(os.path.join(repo, "docs", "cli_reference.md")) as f:
        assert f.read() == text, (
            "docs/cli_reference.md is stale — regenerate with "
            "`python docs/generate_cli_docs.py > docs/cli_reference.md`"
        )
    for name, obj in vars(cli_args).items():
        if (
            dataclasses.is_dataclass(obj)
            and isinstance(obj, type)
            and not name.startswith("_")
        ):
            assert f"## {name}" in text, name
