import pytest

from areal_tpu.api.cli_args import (
    GRPOConfig,
    SFTConfig,
    from_dict,
    load_expr_config,
    parse_cli_args,
)


def test_from_dict_nested_coercion():
    cfg = from_dict(
        GRPOConfig,
        {
            "experiment_name": "e",
            "trial_name": "t",
            "actor": {
                "optimizer": {"lr": "1e-4"},
                "eps_clip": "0.3",
                "ppo_n_minibatches": "2",
            },
            "gconfig": {"max_new_tokens": 128, "temperature": 1},
        },
    )
    assert cfg.actor.eps_clip == 0.3
    assert cfg.actor.ppo_n_minibatches == 2
    assert cfg.actor.optimizer.lr == 1e-4
    assert cfg.gconfig.temperature == 1.0
    assert isinstance(cfg.gconfig.temperature, float)


def test_from_dict_unknown_key_raises():
    with pytest.raises(ValueError, match="Unknown config keys"):
        from_dict(SFTConfig, {"not_a_key": 1})


def test_yaml_plus_overrides(tmp_path):
    p = tmp_path / "c.yaml"
    p.write_text(
        "experiment_name: exp\ntrial_name: t0\nactor:\n  eps_clip: 0.1\n"
    )
    data, _ = parse_cli_args(
        ["--config", str(p), "actor.eps_clip=0.25", "seed=7", "async_training=false"]
    )
    cfg = from_dict(GRPOConfig, data)
    assert cfg.actor.eps_clip == 0.25
    assert cfg.seed == 7
    assert cfg.async_training is False


def test_load_expr_config(tmp_path):
    p = tmp_path / "c.yaml"
    p.write_text("experiment_name: exp\ntrial_name: t0\n")
    cfg, path = load_expr_config(["--config", str(p)], SFTConfig)
    assert cfg.experiment_name == "exp"
    assert path == str(p)
    # experiment/trial names propagate into sub-configs
    assert cfg.saver.experiment_name == "exp"
    assert cfg.stats_logger.trial_name == "t0"


def test_override_without_config_file():
    data, _ = parse_cli_args(["total_train_epochs=3"])
    cfg = from_dict(SFTConfig, data)
    assert cfg.total_train_epochs == 3


def test_bad_override():
    with pytest.raises(ValueError):
        parse_cli_args(["keynovalue"])


def test_cli_docs_generator_covers_all_configs():
    """docs/generate_cli_docs.py emits a section per config dataclass."""
    import dataclasses
    import io
    import importlib.util
    import os

    from areal_tpu.api import cli_args

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "gen_cli_docs", os.path.join(repo, "docs", "generate_cli_docs.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    buf = io.StringIO()
    mod.main(out=buf)
    text = buf.getvalue()
    # the committed reference must match the generator (no hand edits /
    # no stale docs after a cli_args change)
    with open(os.path.join(repo, "docs", "cli_reference.md")) as f:
        assert f.read() == text, (
            "docs/cli_reference.md is stale — regenerate with "
            "`python docs/generate_cli_docs.py > docs/cli_reference.md`"
        )
    for name, obj in vars(cli_args).items():
        if (
            dataclasses.is_dataclass(obj)
            and isinstance(obj, type)
            and not name.startswith("_")
        ):
            assert f"## {name}" in text, name


# ---------------------------------------------------------------------------
# Reference-YAML compatibility (round-2 verdict item 9: field-by-field audit
# vs areal/api/cli_args.py — aliases map, dropped knobs warn, typos raise).
# ---------------------------------------------------------------------------


def test_reference_train_engine_keys_alias_and_ignore():
    import warnings

    from areal_tpu.api.cli_args import TrainEngineConfig, from_dict

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        cfg = from_dict(TrainEngineConfig, {
            "path": "/m",
            # reference spellings:
            "dtype": "float32",
            "grad_reduce_dtype": "float32",
            "gradient_checkpointing": False,
            "use_lora": True,
            "lora_rank": 16,
            "lora_alpha": 32,
            "target_modules": ["q_proj", "v_proj"],
            "peft_type": "lora",
            "disable_dropout": True,
            "weight_update_mode": "disk",
        })
    assert cfg.backend.param_dtype == "float32"
    assert cfg.backend.grad_acc_dtype == "float32"
    assert cfg.backend.remat is False
    assert cfg.lora is not None
    assert cfg.lora.rank == 16 and cfg.lora.alpha == 32
    assert tuple(cfg.lora.target_modules) == ("q_proj", "v_proj")
    assert any("ignored on TPU" in str(x.message) for x in w)


def test_reference_use_lora_false_disables_adapters():
    from areal_tpu.api.cli_args import TrainEngineConfig, from_dict

    cfg = from_dict(
        TrainEngineConfig,
        {"path": "/m", "use_lora": False, "lora_rank": 16, "lora_alpha": 32},
    )
    assert cfg.lora is None


def test_reference_optimizer_and_sglang_sections():
    from areal_tpu.api.cli_args import GRPOConfig, from_dict

    cfg = from_dict(GRPOConfig, {
        "experiment_name": "x", "trial_name": "t",
        "actor": {"path": "/m", "optimizer": {
            "lr": 1e-4,
            "lr_scheduler_type": "cosine",
            "offload": False,
            "initial_loss_scale": 65536.0,  # fp16-only: ignored
        }},
        # the reference server section feeds our JAX server config
        "sglang": {
            "model_path": "/m",
            "dtype": "float32",
            "context_length": 2048,
            "max_running_requests": 32,
            "mem_fraction_static": 0.8,
            "attention_backend": "fa3",  # no JAX counterpart: ignored
        },
    })
    assert cfg.actor.optimizer.lr_scheduler.type == "cosine"
    assert cfg.server.max_seq_len == 2048
    assert cfg.server.max_batch_size == 32
    assert cfg.server.hbm_utilization == 0.8
    assert cfg.server.dtype == "float32"


def test_unknown_keys_still_raise():
    from areal_tpu.api.cli_args import TrainEngineConfig, from_dict

    with pytest.raises(ValueError, match="Unknown config keys"):
        from_dict(TrainEngineConfig, {"path": "/m", "not_a_real_knob": 1})
