"""Saver/Evaluator frequency control + full recover dump/load roundtrip."""

import os

import numpy as np
import pytest

from areal_tpu.api.cli_args import (
    EvaluatorConfig,
    OptimizerConfig,
    RecoverConfig,
    SaverConfig,
    TrainEngineConfig,
)
from areal_tpu.api.io_struct import FinetuneSpec, StepInfo
from areal_tpu.engine.sft.lm_engine import TPULMEngine
from areal_tpu.models.config import tiny_config
from areal_tpu.utils.dataloader import StatefulDataLoader
from areal_tpu.utils.recover import RecoverHandler, check_if_recover
from areal_tpu.utils.saver import Evaluator, FreqTimer, Saver


def make_engine():
    cfg = TrainEngineConfig(
        path="", init_from_scratch=True, optimizer=OptimizerConfig(lr=1e-3)
    )
    cfg.backend.param_dtype = "float32"
    cfg.backend.pad_mb_to_multiple = 32
    eng = TPULMEngine(cfg)
    eng.initialize(
        None,
        None,
        model_config=tiny_config(
            vocab_size=128,
            hidden_size=32,
            intermediate_size=64,
            num_hidden_layers=2,
            num_attention_heads=4,
            num_key_value_heads=2,
        ),
    )
    return eng


def step(i, spe=4):
    return StepInfo(epoch=i // spe, epoch_step=i % spe, global_step=i, steps_per_epoch=spe)


def test_freq_timer_steps():
    t = FreqTimer(freq_steps=3)
    fired = [t.should_fire(step(i), False) for i in range(6)]
    assert fired == [False, False, True, False, False, True]


def test_freq_timer_epochs():
    t = FreqTimer(freq_epochs=1)
    assert not t.should_fire(step(1), False)
    assert t.should_fire(step(3), True)


def test_saver_fires_on_freq(tmp_path):
    eng = make_engine()
    ft = FinetuneSpec(total_train_epochs=1, dataset_size=16, train_batch_size=4)
    saver = Saver(
        SaverConfig(
            freq_steps=2,
            experiment_name="s",
            trial_name="t",
            fileroot=str(tmp_path),
        ),
        ft,
    )
    assert saver.save(eng, step(0)) is None
    path = saver.save(eng, step(1))
    assert path is not None and os.path.isfile(os.path.join(path, "model.safetensors"))
    eng.destroy()


def test_check_if_recover_env(monkeypatch):
    assert not check_if_recover(RecoverConfig(mode="disabled"))
    assert check_if_recover(RecoverConfig(mode="resume"))
    monkeypatch.setenv("AREAL_RECOVER_RUN", "1")
    assert check_if_recover(RecoverConfig(mode="fault"))
    monkeypatch.delenv("AREAL_RECOVER_RUN")
    assert not check_if_recover(RecoverConfig(mode="fault"), run_id=0)
    assert check_if_recover(RecoverConfig(mode="fault"), run_id=1)


@pytest.mark.slow
def test_recover_roundtrip(tmp_path):
    rng = np.random.default_rng(0)
    data = dict(
        input_ids=rng.integers(1, 128, size=(4, 16)).astype(np.int32),
        attention_mask=np.ones((4, 16), np.int32),
        loss_mask=np.ones((4, 16), np.int32),
    )
    ft = FinetuneSpec(total_train_epochs=1, dataset_size=16, train_batch_size=4)

    eng = make_engine()
    eng.train_lm(data)  # one step so optimizer state is non-trivial
    eng.set_version(5)
    dl = StatefulDataLoader(list(range(16)), batch_size=4, seed=3)
    it = iter(dl)
    next(it)
    saver = Saver(SaverConfig(freq_steps=1), ft)
    handler = RecoverHandler(RecoverConfig(mode="fault", freq_steps=1), ft)
    root = handler.dump(
        eng,
        step(2),
        saver,
        None,
        dl,
        fileroot=str(tmp_path),
        experiment_name="e",
        trial_name="t",
        config=None,
        force=True,
    )
    assert root is not None
    ref_params = eng.params

    eng2 = make_engine()
    dl2 = StatefulDataLoader(list(range(16)), batch_size=4, seed=3)
    handler2 = RecoverHandler(RecoverConfig(mode="fault"), ft)
    info = handler2.load(
        eng2,
        None,
        None,
        dl2,
        fileroot=str(tmp_path),
        experiment_name="e",
        trial_name="t",
    )
    assert info is not None
    assert info.last_step_info.global_step == 2
    assert dl2.state_dict() == dl.state_dict()
    # weights restored exactly
    import jax

    for a, b in zip(
        jax.tree_util.tree_leaves(ref_params), jax.tree_util.tree_leaves(eng2.params)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # training continues from restored state without error
    stats = eng2.train_lm(data)
    assert np.isfinite(stats["loss"])
    eng.destroy()
    eng2.destroy()


def test_config_hash_mismatch_refuses(tmp_path):
    ft = FinetuneSpec(total_train_epochs=1, dataset_size=16, train_batch_size=4)
    eng = make_engine()
    handler = RecoverHandler(RecoverConfig(mode="fault", freq_steps=1), ft)
    cfg_a = SaverConfig(freq_steps=1)
    cfg_b = SaverConfig(freq_steps=2)
    handler.dump(
        eng,
        step(0),
        None,
        None,
        None,
        fileroot=str(tmp_path),
        experiment_name="e",
        trial_name="t",
        config=cfg_a,
        force=True,
    )
    with pytest.raises(RuntimeError, match="config hash"):
        handler.load(
            eng,
            fileroot=str(tmp_path),
            experiment_name="e",
            trial_name="t",
            config=cfg_b,
        )
    eng.destroy()


# ---------------------------------------------------------------------------
# fast recover-cycle tests (no real engine): env protocol round-trip and
# corrupted/partial recover state must refuse to resume, not crash
# ---------------------------------------------------------------------------


class _DummyEngine:
    """save/load stand-in: records a marker file as its 'checkpoint'."""

    def __init__(self):
        self.loaded = None

    def save(self, meta):
        os.makedirs(meta.path, exist_ok=True)
        with open(os.path.join(meta.path, "ckpt.marker"), "w") as f:
            f.write("ok")

    def load(self, meta):
        path = os.path.join(meta.path, "ckpt.marker")
        with open(path) as f:
            if f.read() != "ok":
                raise ValueError(f"corrupt checkpoint at {path}")
        self.loaded = meta.path


class _DummyLoader:
    def __init__(self, pos=0):
        self.pos = pos

    def state_dict(self):
        return {"pos": self.pos}

    def load_state_dict(self, d):
        self.pos = d["pos"]


def _dump_dummy(tmp_path, config=None):
    ft = FinetuneSpec(total_train_epochs=1, dataset_size=16, train_batch_size=4)
    handler = RecoverHandler(RecoverConfig(mode="fault", freq_steps=1), ft)
    root = handler.dump(
        _DummyEngine(),
        step(3),
        None,
        None,
        _DummyLoader(pos=7),
        fileroot=str(tmp_path),
        experiment_name="e",
        trial_name="t",
        config=config,
        force=True,
    )
    assert root is not None
    return handler, root


def test_recover_env_protocol_roundtrip(tmp_path, monkeypatch):
    """The launcher-relaunch cycle: dump, relaunch with AREAL_RECOVER_RUN
    set, check_if_recover says resume, load restores the loop state."""
    cfg = RecoverConfig(mode="fault", freq_steps=1)
    handler, root = _dump_dummy(tmp_path)
    # without the env (and run_id 0) a fault-mode run starts fresh
    monkeypatch.delenv("AREAL_RECOVER_RUN", raising=False)
    assert not check_if_recover(cfg, run_id=0)
    # the launcher relaunches the failed trial with the env set
    monkeypatch.setenv("AREAL_RECOVER_RUN", "1")
    assert check_if_recover(cfg)
    eng, dl = _DummyEngine(), _DummyLoader()
    info = handler.load(
        eng,
        None,
        None,
        dl,
        fileroot=str(tmp_path),
        experiment_name="e",
        trial_name="t",
    )
    assert info is not None and info.last_step_info.global_step == 3
    assert dl.pos == 7  # dataloader position fast-forwarded
    assert eng.loaded is not None


def test_recover_refuses_corrupted_info_json(tmp_path):
    from areal_tpu.utils.recover import RecoverStateCorrupted

    handler, root = _dump_dummy(tmp_path)
    # the commit marker lives at the recover ROOT (the returned path is the
    # per-step dump dir it references)
    marker_root = handler.recover_root(str(tmp_path), "e", "t")
    with open(os.path.join(marker_root, "recover_info.json"), "w") as f:
        f.write('{"last_step_info": {"epo')  # truncated mid-write
    with pytest.raises(RecoverStateCorrupted, match="refusing to resume"):
        handler.load(
            _DummyEngine(),
            fileroot=str(tmp_path),
            experiment_name="e",
            trial_name="t",
        )


def test_recover_refuses_corrupted_loop_state(tmp_path):
    from areal_tpu.utils.recover import RecoverStateCorrupted

    handler, root = _dump_dummy(tmp_path)
    with open(os.path.join(root, "loop_state.pkl"), "wb") as f:
        f.write(b"\x80\x04not a pickle")
    with pytest.raises(RecoverStateCorrupted, match="refusing to resume"):
        handler.load(
            _DummyEngine(),
            None,
            None,
            _DummyLoader(),
            fileroot=str(tmp_path),
            experiment_name="e",
            trial_name="t",
        )


def test_recover_refuses_partial_checkpoint(tmp_path):
    from areal_tpu.utils.recover import RecoverStateCorrupted

    handler, root = _dump_dummy(tmp_path)
    # the engine checkpoint is partial: marker content destroyed
    with open(os.path.join(root, "engine", "ckpt.marker"), "w") as f:
        f.write("partial")
    with pytest.raises(RecoverStateCorrupted, match="partial or corrupted"):
        handler.load(
            _DummyEngine(),
            fileroot=str(tmp_path),
            experiment_name="e",
            trial_name="t",
        )


def test_recover_missing_info_is_fresh_start(tmp_path):
    ft = FinetuneSpec(total_train_epochs=1, dataset_size=16, train_batch_size=4)
    handler = RecoverHandler(RecoverConfig(mode="fault"), ft)
    assert (
        handler.load(
            _DummyEngine(),
            fileroot=str(tmp_path),
            experiment_name="e",
            trial_name="t",
        )
        is None
    )


def test_same_step_redump_crash_preserves_committed_dump(tmp_path, monkeypatch):
    """A graceful shutdown re-dumps the SAME step a periodic dump already
    committed; a crash mid-restage must not have touched the committed
    dump — the restage goes to a distinct suffixed directory."""
    from areal_tpu.utils import chaos

    ft = FinetuneSpec(total_train_epochs=1, dataset_size=16, train_batch_size=4)
    handler = RecoverHandler(RecoverConfig(mode="fault", freq_steps=1), ft)
    kw = dict(fileroot=str(tmp_path), experiment_name="e", trial_name="t")
    handler.dump(
        _DummyEngine(), step(2), None, None, _DummyLoader(pos=5), force=True, **kw
    )
    monkeypatch.setenv(chaos.CRASH_ENV, "mid-checkpoint")
    chaos.reset_crash_points()
    with pytest.raises(chaos.InjectedCrash):
        handler.dump(
            _DummyEngine(), step(2), None, None, _DummyLoader(pos=5),
            force=True, **kw,
        )
    monkeypatch.delenv(chaos.CRASH_ENV)
    chaos.reset_crash_points()
    eng, dl = _DummyEngine(), _DummyLoader()
    info = handler.load(eng, None, None, dl, **kw)
    assert info is not None and info.last_step_info.global_step == 2
    assert dl.pos == 5 and eng.loaded is not None
    # a successful same-step re-dump commits under the suffixed name
    root2 = handler.dump(
        _DummyEngine(), step(2), None, None, _DummyLoader(pos=5), force=True, **kw
    )
    assert os.path.basename(root2) == "dump_globalstep2.1"
    assert handler.load(_DummyEngine(), **kw).last_step_info.global_step == 2


def test_recover_dump_keeps_previous_until_commit(tmp_path):
    """Crash consistency of the dump itself: a new dump stages into its own
    directory and the old one survives until the marker flips; retention
    keeps ``keep_dumps`` committed dumps so a corrupted newest dump has a
    fallback landing spot, and GC's anything older."""
    ft = FinetuneSpec(total_train_epochs=1, dataset_size=16, train_batch_size=4)
    handler = RecoverHandler(RecoverConfig(mode="fault", freq_steps=1), ft)
    kw = dict(fileroot=str(tmp_path), experiment_name="e", trial_name="t")
    root1 = handler.dump(_DummyEngine(), step(1), None, None, None, force=True, **kw)
    assert os.path.basename(root1) == "dump_globalstep1"
    root2 = handler.dump(_DummyEngine(), step(2), None, None, None, force=True, **kw)
    assert os.path.isdir(root2)
    # default keep_dumps=2: the previous dump survives as disaster fallback
    assert os.path.isdir(root1)
    root3 = handler.dump(_DummyEngine(), step(3), None, None, None, force=True, **kw)
    assert os.path.isdir(root3) and os.path.isdir(root2)
    assert not os.path.isdir(root1)  # beyond retention after the new commit
    info = handler.load(_DummyEngine(), **kw)
    assert info.last_step_info.global_step == 3


def test_recover_dump_keep_dumps_one_gcs_previous(tmp_path):
    """keep_dumps=1 restores the old disk-frugal behavior: only the newest
    committed dump survives."""
    ft = FinetuneSpec(total_train_epochs=1, dataset_size=16, train_batch_size=4)
    handler = RecoverHandler(
        RecoverConfig(mode="fault", freq_steps=1, keep_dumps=1), ft
    )
    kw = dict(fileroot=str(tmp_path), experiment_name="e", trial_name="t")
    root1 = handler.dump(_DummyEngine(), step(1), None, None, None, force=True, **kw)
    root2 = handler.dump(_DummyEngine(), step(2), None, None, None, force=True, **kw)
    assert os.path.isdir(root2)
    assert not os.path.isdir(root1)
    info = handler.load(_DummyEngine(), **kw)
    assert info.last_step_info.global_step == 2


# ---------------------------------------------------------------------------
# checkpoint retention GC + latest pointer
# ---------------------------------------------------------------------------


def _retention_saver(tmp_path, **knobs):
    ft = FinetuneSpec(total_train_epochs=2, dataset_size=64, train_batch_size=4)
    return Saver(
        SaverConfig(
            freq_steps=1,
            experiment_name="e",
            trial_name="t",
            fileroot=str(tmp_path),
            **knobs,
        ),
        ft,
    )


def _saved_steps(saver):
    import re

    names = [
        n for n in os.listdir(saver.save_root()) if n.startswith("epoch")
    ]
    return sorted(
        int(re.search(r"globalstep(\d+)$", n).group(1)) for n in names
    )


def test_retention_gc_keep_last_and_keep_every(tmp_path):
    saver = _retention_saver(tmp_path, keep_last=2, keep_every=4)
    eng = _DummyEngine()
    for i in range(8):
        assert saver.save(eng, step(i, spe=16), force=True) is not None
    # newest 2 (6,7) + keep_every multiples (0,4)
    assert _saved_steps(saver) == [0, 4, 6, 7]
    # the latest pointer names the newest checkpoint
    latest = saver.latest_checkpoint()
    assert latest is not None and latest.endswith("globalstep7")


def test_retention_gc_protects_recover_named_checkpoint(tmp_path):
    """The checkpoint the recover info references must survive GC even when
    retention would delete it — deleting it strands the next resume."""
    saver = _retention_saver(tmp_path, keep_last=1)
    handler = RecoverHandler(RecoverConfig(mode="fault", freq_steps=1), None)
    kw = dict(fileroot=str(tmp_path), experiment_name="e", trial_name="t")
    eng = _DummyEngine()
    saver.save(eng, step(3, spe=16), force=True)
    # recover info records last_save_path = globalstep3
    handler.dump(eng, step(3, spe=16), saver, None, None, force=True, **kw)
    assert handler.protected_paths(**kw) == {saver.last_save_path}
    for i in (4, 5):
        saver.save(
            eng,
            step(i, spe=16),
            force=True,
            protect=handler.protected_paths(**kw),
        )
    # keep_last=1 would leave only globalstep5, but 3 is recover-protected
    assert _saved_steps(saver) == [3, 5]


def test_retention_gc_disabled_keeps_everything(tmp_path):
    saver = _retention_saver(tmp_path)
    eng = _DummyEngine()
    for i in range(4):
        saver.save(eng, step(i, spe=16), force=True)
    assert _saved_steps(saver) == [0, 1, 2, 3]
    assert saver.gc() == []


# ---------------------------------------------------------------------------
# stats logger resume dedup
# ---------------------------------------------------------------------------


def _stats_logger(tmp_path):
    from areal_tpu.api.cli_args import StatsLoggerConfig
    from areal_tpu.utils.stats_logger import StatsLogger

    return StatsLogger(
        StatsLoggerConfig(
            experiment_name="e", trial_name="t", fileroot=str(tmp_path)
        ),
        rank=0,
    )


def _stats_lines(tmp_path):
    import json

    path = os.path.join(str(tmp_path), "e", "t", "logs", "stats.jsonl")
    with open(path) as f:
        return [json.loads(line) for line in f]


def test_stats_logger_never_double_logs_a_step(tmp_path):
    lg = _stats_logger(tmp_path)
    for i in range(3):
        lg.commit(0, i, i, {"x": float(i)})
    lg.close()
    # RECOVERY restart (load_state_dict arms the dedup floor) replays
    # steps 1-2 (recovered trainer re-runs them), then moves on to 3
    lg2 = _stats_logger(tmp_path)
    lg2.load_state_dict({})  # RecoverHandler.load does this
    assert lg2.last_logged_step == 2
    lg2.commit(0, 1, 1, {"x": 100.0})  # replay: skipped
    lg2.commit(0, 2, 2, {"x": 200.0})  # replay: skipped
    lg2.commit(0, 3, 3, {"x": 3.0})
    lg2.close()
    recs = _stats_lines(tmp_path)
    assert [r["global_step"] for r in recs] == [0, 1, 2, 3]
    assert recs[1]["x"] == 1.0  # the original record, not the replay


def test_stats_logger_fresh_run_over_old_logs_is_not_deduped(tmp_path):
    """A brand-new run reusing an experiment/trial name (no recovery) must
    keep logging — the dedup floor only arms on load_state_dict."""
    lg = _stats_logger(tmp_path)
    lg.commit(0, 0, 0, {"x": 0.0})
    lg.close()
    lg2 = _stats_logger(tmp_path)  # fresh run, same names, no recovery
    lg2.commit(0, 0, 0, {"x": 10.0})
    lg2.close()
    assert [r["x"] for r in _stats_lines(tmp_path)] == [0.0, 10.0]


def test_stats_logger_truncates_torn_tail_on_reopen(tmp_path):
    lg = _stats_logger(tmp_path)
    lg.commit(0, 0, 0, {"x": 0.0})
    lg.commit(0, 1, 1, {"x": 1.0})
    lg.close()
    path = os.path.join(str(tmp_path), "e", "t", "logs", "stats.jsonl")
    with open(path, "a") as f:
        f.write('{"epoch": 0, "step": 2, "global_st')  # crash mid-write
    lg2 = _stats_logger(tmp_path)
    lg2.load_state_dict({})
    assert lg2.last_logged_step == 1
    lg2.commit(0, 2, 2, {"x": 2.0})
    lg2.close()
    recs = _stats_lines(tmp_path)  # parses cleanly: torn tail was truncated
    assert [r["global_step"] for r in recs] == [0, 1, 2]


# ---------------------------------------------------------------------------
# dataloader deterministic resume
# ---------------------------------------------------------------------------


def _collect(dl, n=None):
    out = []
    it = iter(dl)
    while n is None or len(out) < n:
        try:
            out.append(tuple(next(it)))
        except StopIteration:
            if n is None:
                return out
            it = iter(dl)
    return out


def test_dataloader_resume_stream_identical_to_uninterrupted(tmp_path):
    data = list(range(50))
    ref = _collect(StatefulDataLoader(data, 4, seed=7), n=24)  # 2 epochs
    # interrupted run: consume 7 batches, snapshot, 'crash'
    dl = StatefulDataLoader(data, 4, seed=7)
    first = _collect(dl, n=7)
    snap = dl.state_dict()
    # resumed process: fresh loader over the same dataset, restore cursor
    dl2 = StatefulDataLoader(data, 4, seed=7)
    dl2.load_state_dict(snap)
    rest = _collect(dl2, n=24 - 7)
    assert first + rest == ref


def test_dataloader_refuses_mismatched_dataset(tmp_path):
    dl = StatefulDataLoader(list(range(16)), 4, seed=1)
    snap = dl.state_dict()
    other = StatefulDataLoader(list(range(20)), 4, seed=1)
    with pytest.raises(ValueError, match="dataset_size"):
        other.load_state_dict(snap)
    # a batch-size change is NOT a refusal — the sample cursor remaps onto
    # any batch size (elastic resume; see test_dataset_and_loader.py for
    # the stream-identity pins)
    rebatched = StatefulDataLoader(list(range(16)), 8, seed=1)
    rebatched.load_state_dict(snap)


# ---------------------------------------------------------------------------
# elastic resume: batch-size / host-count changes remap the sample cursor
# ---------------------------------------------------------------------------


def _flat(batches):
    return [s for b in batches for s in b]


def test_dataloader_resumes_at_different_batch_size(tmp_path):
    """The elastic pin: a cursor saved at batch size 4 resumes at batch
    size 6 with NO sample replayed and NONE skipped — the flattened
    sample stream is identical to the uninterrupted one."""
    data = list(range(24))
    ref = _flat(_collect(StatefulDataLoader(data, 4, seed=9), n=6))  # epoch 0
    dl = StatefulDataLoader(data, 4, seed=9)
    first = _collect(dl, n=3)  # 12 samples consumed
    snap = dl.state_dict()
    dl2 = StatefulDataLoader(data, 6, seed=9)
    dl2.load_state_dict(snap)
    rest = _collect(dl2, n=2)  # 12 remaining samples at the new batch size
    assert all(len(b) == 6 for b in rest)
    assert _flat(first) + _flat(rest) == ref


def test_dataloader_resumes_at_different_host_count(tmp_path):
    """A replacement trainer with half the hosts consumes half the global
    batch (8 -> 4): the sample stream continues exactly where it stopped,
    across the epoch boundary."""
    data = list(range(32))
    ref = _flat(_collect(StatefulDataLoader(data, 8, seed=5), n=8))  # 2 epochs
    dl = StatefulDataLoader(data, 8, seed=5)
    first = _collect(dl, n=3)  # 24 samples into epoch 0
    snap = dl.state_dict()
    dl2 = StatefulDataLoader(data, 4, seed=5)
    dl2.load_state_dict(snap)
    rest = _collect(dl2, n=2 + 8)  # rest of epoch 0 (8 samples) + epoch 1
    assert _flat(first) + _flat(rest) == ref


def test_dataloader_legacy_batch_cursor_remaps(tmp_path):
    """Pre-elastic states counted BATCHES; they remap through their saved
    batch size onto the sample cursor."""
    data = list(range(24))
    ref = _flat(_collect(StatefulDataLoader(data, 4, seed=2), n=6))
    legacy = {"epoch": 0, "batch_in_epoch": 3, "seed": 2, "batch_size": 4,
              "dataset_size": 24}
    dl = StatefulDataLoader(data, 4, seed=2)
    dl.load_state_dict(legacy)
    assert _flat(_collect(dl, n=3)) == ref[12:]


def test_dataloader_refusals_name_exact_field(tmp_path):
    from areal_tpu.utils.dataloader import IncompatibleResumeState

    dl = StatefulDataLoader(list(range(16)), 4, seed=1)
    with pytest.raises(IncompatibleResumeState, match="dataset_size"):
        dl.load_state_dict(
            {"epoch": 0, "sample_in_epoch": 0, "dataset_size": 999}
        )
    with pytest.raises(IncompatibleResumeState, match="batch_size"):
        dl.load_state_dict({"epoch": 0, "batch_in_epoch": 2})
    with pytest.raises(IncompatibleResumeState, match="sample_in_epoch"):
        dl.load_state_dict(
            {"epoch": 0, "sample_in_epoch": 17, "dataset_size": 16}
        )


# ---------------------------------------------------------------------------
# AREAL_CHAOS_FS: injected filesystem faults through the atomic helpers
# ---------------------------------------------------------------------------


def test_fs_fault_grammar(tmp_path, monkeypatch):
    import errno

    from areal_tpu.utils import chaos
    from areal_tpu.utils.fs import atomic_write_text

    target = str(tmp_path / "target.txt")
    atomic_write_text(target, "committed")
    monkeypatch.setenv(chaos.FS_CHAOS_ENV, "target.txt:eio@2")
    chaos.reset_fs_faults()
    atomic_write_text(target, "first write passes")  # @2: fires on the 2nd
    with pytest.raises(OSError) as ei:
        atomic_write_text(target, "never lands")
    assert ei.value.errno == errno.EIO
    # the fault fired BEFORE the rename: the previous commit is intact
    assert open(target).read() == "first write passes"
    monkeypatch.setenv(chaos.FS_CHAOS_ENV, "target.txt:bogus")
    chaos.reset_fs_faults()
    with pytest.raises(ValueError, match="bogus"):
        atomic_write_text(target, "x")
    chaos.reset_fs_faults()


def test_enospc_mid_dump_preserves_committed_checkpoint(tmp_path, monkeypatch):
    """The satellite pin: a dump that hits ENOSPC leaves the PREVIOUS
    committed checkpoint fully intact and resumable; once space returns,
    dumping and resuming proceed normally."""
    import errno

    from areal_tpu.utils import chaos

    ft = FinetuneSpec(total_train_epochs=1, dataset_size=16, train_batch_size=4)
    handler = RecoverHandler(RecoverConfig(mode="fault", freq_steps=1), ft)
    kw = dict(fileroot=str(tmp_path), experiment_name="e", trial_name="t")
    handler.dump(
        _DummyEngine(), step(3), None, None, _DummyLoader(pos=7), force=True, **kw
    )
    monkeypatch.setenv(chaos.FS_CHAOS_ENV, "dump_globalstep4:enospc")
    chaos.reset_fs_faults()
    with pytest.raises(OSError) as ei:
        handler.dump(
            _DummyEngine(), step(4), None, None, _DummyLoader(pos=9),
            force=True, **kw,
        )
    assert ei.value.errno == errno.ENOSPC
    monkeypatch.delenv(chaos.FS_CHAOS_ENV)
    chaos.reset_fs_faults()
    eng, dl = _DummyEngine(), _DummyLoader()
    info = handler.load(eng, None, None, dl, **kw)
    assert info is not None and info.last_step_info.global_step == 3
    assert dl.pos == 7 and eng.loaded is not None
    # space is back: the next dump commits and supersedes
    handler.dump(
        _DummyEngine(), step(4), None, None, _DummyLoader(pos=9), force=True, **kw
    )
    assert handler.load(_DummyEngine(), **kw).last_step_info.global_step == 4


def test_short_write_on_marker_preserves_previous_marker(tmp_path, monkeypatch):
    """A torn write of the commit marker itself must leave the previous
    marker (and therefore the previous resume point) in force."""
    from areal_tpu.utils import chaos

    ft = FinetuneSpec(total_train_epochs=1, dataset_size=16, train_batch_size=4)
    handler = RecoverHandler(RecoverConfig(mode="fault", freq_steps=1), ft)
    kw = dict(fileroot=str(tmp_path), experiment_name="e", trial_name="t")
    handler.dump(
        _DummyEngine(), step(2), None, None, _DummyLoader(pos=5), force=True, **kw
    )
    monkeypatch.setenv(chaos.FS_CHAOS_ENV, "recover_info.json:short")
    chaos.reset_fs_faults()
    with pytest.raises(OSError):
        handler.dump(
            _DummyEngine(), step(3), None, None, _DummyLoader(pos=6),
            force=True, **kw,
        )
    monkeypatch.delenv(chaos.FS_CHAOS_ENV)
    chaos.reset_fs_faults()
    info = handler.load(_DummyEngine(), None, None, _DummyLoader(), **kw)
    assert info is not None and info.last_step_info.global_step == 2


# ---------------------------------------------------------------------------
# corruption-refusing restore: digest fallback to a retained dump
# ---------------------------------------------------------------------------


class _ManifestEngine:
    """Engine stand-in whose checkpoints ARE manifest-format — exercises
    the real digest-verify path in recover without a full TrainEngine."""

    def __init__(self, value=1.0):
        self.value = value
        self.w = np.full((8,), value, np.float32)
        self.loaded_from = None

    def save(self, meta):
        from areal_tpu.utils.checkpoint import save_named

        save_named(meta.path, {"w": self.w})

    def load(self, meta):
        from areal_tpu.utils.checkpoint import load_named

        named, _ = load_named(meta.path)
        self.w = named["w"]
        self.loaded_from = meta.path


def test_bit_flip_in_committed_dump_falls_back_to_retained(tmp_path, monkeypatch):
    """The acceptance pin: a bit-flipped shard in the newest dump is
    refused BY DIGEST before any weights load; the restore falls back to
    the previous retained dump, rewinding the loop state to ITS step, and
    the flight recorder names the failing leaf."""
    import glob

    from areal_tpu.utils import flight_recorder

    ft = FinetuneSpec(total_train_epochs=1, dataset_size=16, train_batch_size=4)
    handler = RecoverHandler(RecoverConfig(mode="fault", freq_steps=1), ft)
    kw = dict(fileroot=str(tmp_path), experiment_name="e", trial_name="t")
    handler.dump(
        _ManifestEngine(1.0), step(1), None, None, _DummyLoader(pos=1),
        force=True, **kw,
    )
    root2 = handler.dump(
        _ManifestEngine(2.0), step(2), None, None, _DummyLoader(pos=2),
        force=True, **kw,
    )
    shard = sorted(glob.glob(os.path.join(root2, "engine", "shards", "*.bin")))[0]
    raw = bytearray(open(shard, "rb").read())
    raw[0] ^= 0x01
    with open(shard, "wb") as f:
        f.write(raw)
    seen = []
    monkeypatch.setattr(
        flight_recorder,
        "record",
        lambda channel, kind, **fields: seen.append((channel, kind, fields)),
    )
    eng, dl = _ManifestEngine(0.0), _DummyLoader()
    info = handler.load(eng, None, None, dl, **kw)
    # fell back to the step-1 dump, with step-1 loop state
    assert info is not None and info.last_step_info.global_step == 1
    assert dl.pos == 1
    np.testing.assert_array_equal(eng.w, np.full((8,), 1.0, np.float32))
    assert eng.loaded_from.endswith(os.path.join("dump_globalstep1", "engine"))
    assert any(
        k == "shard_verify_failed" and f.get("leaf") == "w"
        for _, k, f in seen
    )


def test_all_dumps_corrupt_refuses_loudly(tmp_path):
    from areal_tpu.utils.recover import RecoverStateCorrupted

    ft = FinetuneSpec(total_train_epochs=1, dataset_size=16, train_batch_size=4)
    handler = RecoverHandler(RecoverConfig(mode="fault", freq_steps=1), ft)
    kw = dict(fileroot=str(tmp_path), experiment_name="e", trial_name="t")
    for i in (1, 2):
        root = handler.dump(
            _ManifestEngine(float(i)), step(i), None, None, _DummyLoader(pos=i),
            force=True, **kw,
        )
        import glob

        for shard in glob.glob(os.path.join(root, "engine", "shards", "*.bin")):
            raw = bytearray(open(shard, "rb").read())
            raw[0] ^= 0xFF
            with open(shard, "wb") as f:
                f.write(raw)
    with pytest.raises(RecoverStateCorrupted, match="no retained recover dump"):
        handler.load(_ManifestEngine(0.0), None, None, _DummyLoader(), **kw)


# ---------------------------------------------------------------------------
# Saver latest-pointer validation and fallback
# ---------------------------------------------------------------------------


def _manifest_save_dirs(saver, steps):
    """Write manifest-format checkpoint dirs + latest pointer the way
    Saver.save lays them out."""
    from areal_tpu.utils.checkpoint import save_named
    from areal_tpu.utils.fs import atomic_write_text
    from areal_tpu.utils.saver import LATEST_POINTER

    root = saver.save_root()
    paths = []
    for i in steps:
        path = os.path.join(root, f"epoch0epochstep{i}globalstep{i}")
        save_named(path, {"w": np.full((4,), float(i), np.float32)})
        paths.append(path)
    atomic_write_text(
        os.path.join(root, LATEST_POINTER), os.path.basename(paths[-1]) + "\n"
    )
    return paths


def test_resolve_latest_returns_valid_pointer_target(tmp_path):
    saver = _retention_saver(tmp_path)
    paths = _manifest_save_dirs(saver, [1, 2, 3])
    assert saver.resolve_latest_checkpoint() == paths[-1]


def test_resolve_latest_falls_back_on_dangling_pointer(tmp_path, monkeypatch):
    from areal_tpu.utils import saver as saver_mod
    from areal_tpu.utils.fs import atomic_write_text
    from areal_tpu.utils.saver import LATEST_POINTER

    saver = _retention_saver(tmp_path)
    paths = _manifest_save_dirs(saver, [1, 2])
    atomic_write_text(
        os.path.join(saver.save_root(), LATEST_POINTER), "epoch0epochstep9globalstep9\n"
    )
    warned = []
    monkeypatch.setattr(
        saver_mod.logger, "warning", lambda msg, *a: warned.append(msg % a)
    )
    assert saver.resolve_latest_checkpoint() == paths[-1]
    # the warning is loud and names what was wrong with the pointer
    assert warned and "falling back" in warned[0] and "GC'd" in warned[0]


def test_resolve_latest_falls_back_on_corrupt_target(tmp_path, monkeypatch):
    import glob

    from areal_tpu.utils import saver as saver_mod

    saver = _retention_saver(tmp_path)
    paths = _manifest_save_dirs(saver, [1, 2, 3])
    for shard in glob.glob(os.path.join(paths[-1], "shards", "*.bin")):
        raw = bytearray(open(shard, "rb").read())
        raw[0] ^= 0x10
        with open(shard, "wb") as f:
            f.write(raw)
    warned = []
    monkeypatch.setattr(
        saver_mod.logger, "warning", lambda msg, *a: warned.append(msg % a)
    )
    # newest VERIFYING checkpoint wins — the corrupted pointee is skipped
    assert saver.resolve_latest_checkpoint() == paths[-2]
    assert warned and "digest mismatch" in warned[0]


def test_resolve_latest_none_when_nothing_verifies(tmp_path):
    saver = _retention_saver(tmp_path)
    os.makedirs(saver.save_root(), exist_ok=True)
    assert saver.resolve_latest_checkpoint() is None
