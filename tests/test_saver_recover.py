"""Saver/Evaluator frequency control + full recover dump/load roundtrip."""

import os

import numpy as np
import pytest

from areal_tpu.api.cli_args import (
    EvaluatorConfig,
    OptimizerConfig,
    RecoverConfig,
    SaverConfig,
    TrainEngineConfig,
)
from areal_tpu.api.io_struct import FinetuneSpec, StepInfo
from areal_tpu.engine.sft.lm_engine import TPULMEngine
from areal_tpu.models.config import tiny_config
from areal_tpu.utils.dataloader import StatefulDataLoader
from areal_tpu.utils.recover import RecoverHandler, check_if_recover
from areal_tpu.utils.saver import Evaluator, FreqTimer, Saver


def make_engine():
    cfg = TrainEngineConfig(
        path="", init_from_scratch=True, optimizer=OptimizerConfig(lr=1e-3)
    )
    cfg.backend.param_dtype = "float32"
    cfg.backend.pad_mb_to_multiple = 32
    eng = TPULMEngine(cfg)
    eng.initialize(
        None,
        None,
        model_config=tiny_config(
            vocab_size=128,
            hidden_size=32,
            intermediate_size=64,
            num_hidden_layers=2,
            num_attention_heads=4,
            num_key_value_heads=2,
        ),
    )
    return eng


def step(i, spe=4):
    return StepInfo(epoch=i // spe, epoch_step=i % spe, global_step=i, steps_per_epoch=spe)


def test_freq_timer_steps():
    t = FreqTimer(freq_steps=3)
    fired = [t.should_fire(step(i), False) for i in range(6)]
    assert fired == [False, False, True, False, False, True]


def test_freq_timer_epochs():
    t = FreqTimer(freq_epochs=1)
    assert not t.should_fire(step(1), False)
    assert t.should_fire(step(3), True)


def test_saver_fires_on_freq(tmp_path):
    eng = make_engine()
    ft = FinetuneSpec(total_train_epochs=1, dataset_size=16, train_batch_size=4)
    saver = Saver(
        SaverConfig(
            freq_steps=2,
            experiment_name="s",
            trial_name="t",
            fileroot=str(tmp_path),
        ),
        ft,
    )
    assert saver.save(eng, step(0)) is None
    path = saver.save(eng, step(1))
    assert path is not None and os.path.isfile(os.path.join(path, "model.safetensors"))
    eng.destroy()


def test_check_if_recover_env(monkeypatch):
    assert not check_if_recover(RecoverConfig(mode="disabled"))
    assert check_if_recover(RecoverConfig(mode="resume"))
    monkeypatch.setenv("AREAL_RECOVER_RUN", "1")
    assert check_if_recover(RecoverConfig(mode="fault"))
    monkeypatch.delenv("AREAL_RECOVER_RUN")
    assert not check_if_recover(RecoverConfig(mode="fault"), run_id=0)
    assert check_if_recover(RecoverConfig(mode="fault"), run_id=1)


@pytest.mark.slow
def test_recover_roundtrip(tmp_path):
    rng = np.random.default_rng(0)
    data = dict(
        input_ids=rng.integers(1, 128, size=(4, 16)).astype(np.int32),
        attention_mask=np.ones((4, 16), np.int32),
        loss_mask=np.ones((4, 16), np.int32),
    )
    ft = FinetuneSpec(total_train_epochs=1, dataset_size=16, train_batch_size=4)

    eng = make_engine()
    eng.train_lm(data)  # one step so optimizer state is non-trivial
    eng.set_version(5)
    dl = StatefulDataLoader(list(range(16)), batch_size=4, seed=3)
    it = iter(dl)
    next(it)
    saver = Saver(SaverConfig(freq_steps=1), ft)
    handler = RecoverHandler(RecoverConfig(mode="fault", freq_steps=1), ft)
    root = handler.dump(
        eng,
        step(2),
        saver,
        None,
        dl,
        fileroot=str(tmp_path),
        experiment_name="e",
        trial_name="t",
        config=None,
        force=True,
    )
    assert root is not None
    ref_params = eng.params

    eng2 = make_engine()
    dl2 = StatefulDataLoader(list(range(16)), batch_size=4, seed=3)
    handler2 = RecoverHandler(RecoverConfig(mode="fault"), ft)
    info = handler2.load(
        eng2,
        None,
        None,
        dl2,
        fileroot=str(tmp_path),
        experiment_name="e",
        trial_name="t",
    )
    assert info is not None
    assert info.last_step_info.global_step == 2
    assert dl2.state_dict() == dl.state_dict()
    # weights restored exactly
    import jax

    for a, b in zip(
        jax.tree_util.tree_leaves(ref_params), jax.tree_util.tree_leaves(eng2.params)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # training continues from restored state without error
    stats = eng2.train_lm(data)
    assert np.isfinite(stats["loss"])
    eng.destroy()
    eng2.destroy()


def test_config_hash_mismatch_refuses(tmp_path):
    ft = FinetuneSpec(total_train_epochs=1, dataset_size=16, train_batch_size=4)
    eng = make_engine()
    handler = RecoverHandler(RecoverConfig(mode="fault", freq_steps=1), ft)
    cfg_a = SaverConfig(freq_steps=1)
    cfg_b = SaverConfig(freq_steps=2)
    handler.dump(
        eng,
        step(0),
        None,
        None,
        None,
        fileroot=str(tmp_path),
        experiment_name="e",
        trial_name="t",
        config=cfg_a,
        force=True,
    )
    with pytest.raises(RuntimeError, match="config hash"):
        handler.load(
            eng,
            fileroot=str(tmp_path),
            experiment_name="e",
            trial_name="t",
            config=cfg_b,
        )
    eng.destroy()


# ---------------------------------------------------------------------------
# fast recover-cycle tests (no real engine): env protocol round-trip and
# corrupted/partial recover state must refuse to resume, not crash
# ---------------------------------------------------------------------------


class _DummyEngine:
    """save/load stand-in: records a marker file as its 'checkpoint'."""

    def __init__(self):
        self.loaded = None

    def save(self, meta):
        os.makedirs(meta.path, exist_ok=True)
        with open(os.path.join(meta.path, "ckpt.marker"), "w") as f:
            f.write("ok")

    def load(self, meta):
        path = os.path.join(meta.path, "ckpt.marker")
        with open(path) as f:
            if f.read() != "ok":
                raise ValueError(f"corrupt checkpoint at {path}")
        self.loaded = meta.path


class _DummyLoader:
    def __init__(self, pos=0):
        self.pos = pos

    def state_dict(self):
        return {"pos": self.pos}

    def load_state_dict(self, d):
        self.pos = d["pos"]


def _dump_dummy(tmp_path, config=None):
    ft = FinetuneSpec(total_train_epochs=1, dataset_size=16, train_batch_size=4)
    handler = RecoverHandler(RecoverConfig(mode="fault", freq_steps=1), ft)
    root = handler.dump(
        _DummyEngine(),
        step(3),
        None,
        None,
        _DummyLoader(pos=7),
        fileroot=str(tmp_path),
        experiment_name="e",
        trial_name="t",
        config=config,
        force=True,
    )
    assert root is not None
    return handler, root


def test_recover_env_protocol_roundtrip(tmp_path, monkeypatch):
    """The launcher-relaunch cycle: dump, relaunch with AREAL_RECOVER_RUN
    set, check_if_recover says resume, load restores the loop state."""
    cfg = RecoverConfig(mode="fault", freq_steps=1)
    handler, root = _dump_dummy(tmp_path)
    # without the env (and run_id 0) a fault-mode run starts fresh
    monkeypatch.delenv("AREAL_RECOVER_RUN", raising=False)
    assert not check_if_recover(cfg, run_id=0)
    # the launcher relaunches the failed trial with the env set
    monkeypatch.setenv("AREAL_RECOVER_RUN", "1")
    assert check_if_recover(cfg)
    eng, dl = _DummyEngine(), _DummyLoader()
    info = handler.load(
        eng,
        None,
        None,
        dl,
        fileroot=str(tmp_path),
        experiment_name="e",
        trial_name="t",
    )
    assert info is not None and info.last_step_info.global_step == 3
    assert dl.pos == 7  # dataloader position fast-forwarded
    assert eng.loaded is not None


def test_recover_refuses_corrupted_info_json(tmp_path):
    from areal_tpu.utils.recover import RecoverStateCorrupted

    handler, root = _dump_dummy(tmp_path)
    with open(os.path.join(root, "recover_info.json"), "w") as f:
        f.write('{"last_step_info": {"epo')  # truncated mid-write
    with pytest.raises(RecoverStateCorrupted, match="refusing to resume"):
        handler.load(
            _DummyEngine(),
            fileroot=str(tmp_path),
            experiment_name="e",
            trial_name="t",
        )


def test_recover_refuses_corrupted_loop_state(tmp_path):
    from areal_tpu.utils.recover import RecoverStateCorrupted

    handler, root = _dump_dummy(tmp_path)
    with open(os.path.join(root, "loop_state.pkl"), "wb") as f:
        f.write(b"\x80\x04not a pickle")
    with pytest.raises(RecoverStateCorrupted, match="refusing to resume"):
        handler.load(
            _DummyEngine(),
            None,
            None,
            _DummyLoader(),
            fileroot=str(tmp_path),
            experiment_name="e",
            trial_name="t",
        )


def test_recover_refuses_partial_checkpoint(tmp_path):
    from areal_tpu.utils.recover import RecoverStateCorrupted

    handler, root = _dump_dummy(tmp_path)
    # the engine checkpoint is partial: marker content destroyed
    with open(os.path.join(root, "engine", "ckpt.marker"), "w") as f:
        f.write("partial")
    with pytest.raises(RecoverStateCorrupted, match="partial or corrupted"):
        handler.load(
            _DummyEngine(),
            fileroot=str(tmp_path),
            experiment_name="e",
            trial_name="t",
        )


def test_recover_missing_info_is_fresh_start(tmp_path):
    ft = FinetuneSpec(total_train_epochs=1, dataset_size=16, train_batch_size=4)
    handler = RecoverHandler(RecoverConfig(mode="fault"), ft)
    assert (
        handler.load(
            _DummyEngine(),
            fileroot=str(tmp_path),
            experiment_name="e",
            trial_name="t",
        )
        is None
    )
