"""Topology-independent checkpoint re-sharding (utils/checkpoint.py).

The elastic disaster-recovery pin: a checkpoint written by an N-host mesh
must restore onto ANY replacement topology — fewer hosts, more devices, or
a plain single process — with bit-identical parameters, and corruption must
be refused by digest BEFORE any weight loads, naming the exact leaf.

Multi-host saves are emulated the way the driver tests emulate them: each
"host" contributes its local shard boxes through ``CheckpointWriter.add_shard``
(exactly what ``add_leaf`` does per process on real fleets), so the on-disk
layout is indistinguishable from a genuine 2-host dump.
"""

import json
import os

import numpy as np
import pytest

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from areal_tpu.api.alloc_mode import ParallelStrategy
from areal_tpu.parallel.mesh import make_mesh
from areal_tpu.utils import checkpoint as ckpt
from areal_tpu.utils.checkpoint import (
    CheckpointCorrupted,
    CheckpointWriter,
    MANIFEST_NAME,
    load_named,
    read_manifest,
    save_named,
    tree_digest,
    verify,
    verify_checkpoint_dir,
    verify_or_raise,
)


def _reference_tree(seed=0):
    rng = np.random.default_rng(seed)
    return {
        "w": rng.standard_normal((8, 6)).astype(np.float32),
        "b": rng.standard_normal((4,)).astype(np.float32),
        "step": np.asarray(7, dtype=np.int32),
    }


def _two_host_save(path, tree):
    """Emulated 2-host dump: host0 and host1 each hold half of ``w`` (dp=2
    row split), while ``b`` and ``step`` are replicated — replica 0 writes
    the single full-cover shard, exactly as ``add_leaf`` dedups on fleet."""
    w = CheckpointWriter(path)
    full = tree["w"]
    w.add_shard("w", full.shape, str(full.dtype), [[0, 4], [0, 6]], full[:4])
    w.add_shard("w", full.shape, str(full.dtype), [[4, 8], [0, 6]], full[4:])
    w.add_shard("b", (4,), "float32", [[0, 4]], tree["b"])
    w.add_shard("step", (), "int32", [], tree["step"])
    return w.commit(extras={"opt_steps": 3})


def test_two_host_save_resumes_single_host_bit_identical(tmp_path):
    tree = _reference_tree()
    want = tree_digest(tree)
    _two_host_save(str(tmp_path), tree)
    assert verify(str(tmp_path)) == []
    named, extras = load_named(str(tmp_path))
    assert extras == {"opt_steps": 3}
    assert tree_digest(named) == want
    np.testing.assert_array_equal(named["w"], tree["w"])
    np.testing.assert_array_equal(named["b"], tree["b"])
    assert named["step"].shape == () and int(named["step"]) == 7
    # the 2-way split w needed assembly; b and step read straight through
    assert ckpt.last_load_stats["assembled_leaves"] == 1
    assert ckpt.last_load_stats["direct_shard_reads"] == 2


def test_two_host_save_reshards_onto_four_device_mesh(tmp_path):
    """The N-host -> M-device path: 2-host shard boxes do not line up with
    a dp4 target layout, so leaves assemble once and slice per device —
    and the parameters are still bit-identical."""
    tree = _reference_tree(seed=1)
    want = tree_digest(tree)
    _two_host_save(str(tmp_path), tree)
    mesh = make_mesh(ParallelStrategy(dp=4))
    shardings = {
        "w": NamedSharding(mesh, P("dp")),
        "b": NamedSharding(mesh, P("dp")),
        "step": NamedSharding(mesh, P()),
    }
    named, _ = load_named(str(tmp_path), shardings=shardings)
    for name in ("w", "b", "step"):
        assert isinstance(named[name], jax.Array)
        assert named[name].sharding == shardings[name]
    host = {k: np.asarray(jax.device_get(v)) for k, v in named.items()}
    assert tree_digest(host) == want
    assert ckpt.last_load_stats["assembled_leaves"] >= 2  # w and b re-sliced


def test_matching_topology_stays_on_direct_read_fast_path(tmp_path):
    """Same-mesh resume must NOT regress to gather-and-slice: every device
    slice is exactly covered by one saved shard file and reads directly."""
    mesh = make_mesh(ParallelStrategy(dp=4))
    sh = NamedSharding(mesh, P("dp"))
    src = np.arange(16 * 3, dtype=np.float32).reshape(16, 3)
    arr = jax.device_put(src, sh)
    save_named(str(tmp_path), {"w": arr})
    manifest = read_manifest(str(tmp_path))
    assert len(manifest["leaves"]["w"]["shards"]) == 4
    named, _ = load_named(str(tmp_path), shardings={"w": sh})
    np.testing.assert_array_equal(np.asarray(jax.device_get(named["w"])), src)
    assert ckpt.last_load_stats["assembled_leaves"] == 0
    assert ckpt.last_load_stats["direct_shard_reads"] == 4


def test_replicated_leaf_writes_one_shard(tmp_path):
    """Replicated placements (P()) must not write N identical copies."""
    mesh = make_mesh(ParallelStrategy(dp=4))
    arr = jax.device_put(
        np.ones((5,), np.float32), NamedSharding(mesh, P())
    )
    save_named(str(tmp_path), {"b": arr})
    manifest = read_manifest(str(tmp_path))
    assert len(manifest["leaves"]["b"]["shards"]) == 1


def test_bit_flip_refused_naming_leaf(tmp_path):
    tree = _reference_tree(seed=2)
    manifest = _two_host_save(str(tmp_path), tree)
    victim = manifest["leaves"]["w"]["shards"][1]["file"]
    fpath = os.path.join(str(tmp_path), victim)
    raw = bytearray(open(fpath, "rb").read())
    raw[5] ^= 0x40
    with open(fpath, "wb") as f:
        f.write(raw)
    failures = verify(str(tmp_path))
    assert [f["leaf"] for f in failures] == ["w"]
    assert "digest mismatch" in failures[0]["reason"]
    with pytest.raises(CheckpointCorrupted, match=r"leaf 'w'"):
        verify_or_raise(str(tmp_path))
    # the load path refuses up front too — no partial tree escapes
    with pytest.raises(CheckpointCorrupted, match=r"leaf 'w'"):
        load_named(str(tmp_path))
    ok, why = verify_checkpoint_dir(str(tmp_path))
    assert not ok and "'w'" in why


def test_truncated_shard_refused_naming_leaf(tmp_path):
    tree = _reference_tree(seed=3)
    manifest = _two_host_save(str(tmp_path), tree)
    victim = manifest["leaves"]["b"]["shards"][0]["file"]
    fpath = os.path.join(str(tmp_path), victim)
    with open(fpath, "r+b") as f:
        f.truncate(3)
    failures = verify(str(tmp_path))
    assert [f["leaf"] for f in failures] == ["b"]
    assert "truncated" in failures[0]["reason"]


def test_missing_manifest_means_save_never_committed(tmp_path):
    """A crash before the manifest lands must read as a torn save, not a
    valid-but-empty checkpoint."""
    w = CheckpointWriter(str(tmp_path))
    w.add_shard("w", (2,), "float32", [[0, 2]], np.zeros(2, np.float32))
    # no commit()
    with pytest.raises(CheckpointCorrupted, match="never committed"):
        read_manifest(str(tmp_path))
    ok, why = verify_checkpoint_dir(str(tmp_path))
    assert not ok and "never committed" in why


def test_newer_schema_refused(tmp_path):
    _two_host_save(str(tmp_path), _reference_tree())
    mpath = os.path.join(str(tmp_path), MANIFEST_NAME)
    m = json.load(open(mpath))
    m["schema_version"] = ckpt.MANIFEST_SCHEMA + 1
    with open(mpath, "w") as f:
        json.dump(m, f)
    with pytest.raises(CheckpointCorrupted, match="newer than this build"):
        read_manifest(mpath[: -len("/" + MANIFEST_NAME)])


def test_flight_recorder_names_failing_leaf(tmp_path, monkeypatch):
    """The refusal leaves evidence: which leaf failed, in the flight
    recorder, so the postmortem starts at the corruption."""
    from areal_tpu.utils import flight_recorder

    tree = _reference_tree(seed=4)
    manifest = _two_host_save(str(tmp_path), tree)
    victim = manifest["leaves"]["w"]["shards"][0]["file"]
    fpath = os.path.join(str(tmp_path), victim)
    raw = bytearray(open(fpath, "rb").read())
    raw[0] ^= 0x01
    with open(fpath, "wb") as f:
        f.write(raw)
    seen = []
    monkeypatch.setattr(
        flight_recorder,
        "record",
        lambda channel, kind, **fields: seen.append((channel, kind, fields)),
    )
    with pytest.raises(CheckpointCorrupted):
        verify_or_raise(str(tmp_path))
    assert seen and seen[0][0] == "checkpoint"
    assert seen[0][1] == "shard_verify_failed"
    assert seen[0][2]["leaf"] == "w"


# ---------------------------------------------------------------------------
# engine-level: real TrainEngine across mesh shapes
# ---------------------------------------------------------------------------


def _engine(parallel=None, seed=11):
    from areal_tpu.api.cli_args import OptimizerConfig, TrainEngineConfig
    from areal_tpu.engine.sft.lm_engine import TPULMEngine
    from areal_tpu.models.config import tiny_config

    cfg = TrainEngineConfig(
        path="",
        init_from_scratch=True,
        optimizer=OptimizerConfig(lr=1e-2, gradient_clipping=1.0),
    )
    cfg.backend.pad_mb_to_multiple = 8
    cfg.backend.remat = False
    cfg.backend.param_dtype = "float32"
    eng = TPULMEngine(cfg)
    eng.create_process_group(parallel)
    eng.initialize(None, None, model_config=tiny_config(), seed=seed)
    return eng


def _param_digest(eng) -> str:
    host = {
        name: np.asarray(jax.device_get(leaf))
        for name, leaf in eng._walk_params(eng.params)
    }
    return tree_digest(host)


def _train_one(eng, seed=0):
    rng = np.random.default_rng(seed)
    bs, seqlen, vocab = 4, 12, 128
    input_ids = rng.integers(1, vocab, size=(bs, seqlen)).astype(np.int32)
    attn = np.ones((bs, seqlen), np.int32)
    loss_mask = np.ones((bs, seqlen), np.int32)
    loss_mask[:, 0] = 0
    return eng.train_lm(
        dict(input_ids=input_ids, attention_mask=attn, loss_mask=loss_mask)
    )


@pytest.mark.parametrize(
    "target",
    [
        # dp2tp2 is the only tier-1 variant: it exercises both the dp
        # re-split and a TP partition the source never had, subsuming the
        # others' reshard paths. single/dp4 ride the slow lane — each one
        # compiles two engines, too heavy to run all three per CI pass
        # (the array-level tests above pin 2-host -> 1-host and -> dp4).
        pytest.param(None, id="single", marks=pytest.mark.slow),
        pytest.param(ParallelStrategy(dp=4), id="dp4", marks=pytest.mark.slow),
        pytest.param(ParallelStrategy(dp=2, tp=2), id="dp2tp2"),
    ],
)
def test_engine_sharded_checkpoint_resumes_across_meshes(tmp_path, target):
    """The acceptance pin: a dp2 (2-host-emulated) engine checkpoint
    restores onto a single process, a dp4 mesh, and a dp2tp2 mesh — with
    bit-identical parameter digests, the optimizer step count intact, and
    training able to continue."""
    from areal_tpu.api.io_struct import SaveLoadMeta

    src = _engine(ParallelStrategy(dp=2), seed=11)
    _train_one(src, seed=1)
    want = _param_digest(src)
    want_opt = src._opt_steps
    path = str(tmp_path / "ckpt")
    src.save(SaveLoadMeta(path=path, weight_format="sharded", with_optim=True))

    dst = _engine(target, seed=99)  # different init — the load must win
    assert _param_digest(dst) != want
    dst.load(SaveLoadMeta(path=path, weight_format="sharded", with_optim=True))
    assert _param_digest(dst) == want
    assert dst._opt_steps == want_opt
    stats = _train_one(dst, seed=2)
    assert np.isfinite(stats["loss"])
