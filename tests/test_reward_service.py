"""Sandboxed reward-execution plane (ISSUE 14): worker pool semantics
(rlimits, wall-deadline process-group kills, recycling, bounded
admission), the HTTP service (batch schema, 429+Retry-After, readiness,
drain + flight dump), the breaker-fronted client (chaos-injected faults,
step-exact breaker behavior, local-pool fallback, probe recovery), and
the regression pins for the two satellite bugs (default-executor
starvation in the tool env, orphaned grandchildren in the per-call
sandbox)."""

import asyncio
import json
import os
import threading
import time

import pytest

from areal_tpu.api.cli_args import (
    ChaosConfig,
    CircuitBreakerConfig,
    RewardServiceConfig,
)
from areal_tpu.reward_service.pool import (
    PoolSaturated,
    SandboxWorkerPool,
    get_default_pool,
    shutdown_default_pool,
)
from areal_tpu.utils import flight_recorder


def _alive_and_running(pid: int) -> bool:
    """True only for a pid that exists AND is not a zombie (a zombie is
    dead — merely unreaped by this container's init)."""
    try:
        with open(f"/proc/{pid}/stat") as f:
            return f.read().split()[2] != "Z"
    except (FileNotFoundError, ProcessLookupError):
        return False


@pytest.fixture()
def pool():
    p = SandboxWorkerPool(
        num_workers=2, recycle_after=50, default_timeout=5.0, kill_grace=0.5
    )
    yield p
    p.shutdown()


# ---------------------------------------------------------------------------
# pool semantics
# ---------------------------------------------------------------------------


def test_pool_basic_verdicts(pool):
    r = pool.run("print(input())", stdin="hello")
    assert r.ok and r.output == "hello\n"
    r = pool.run("import sys; sys.exit(3)")
    assert not r.ok and r.returncode == 3
    r = pool.run("raise ValueError('boom')")
    assert not r.ok and "ValueError" in r.output
    # a snippet calling exit() (models do constantly) must not cost a
    # worker respawn: the task runs in a forked child
    before = pool.stats()["tasks_completed"]
    for _ in range(3):
        assert pool.run("exit()").ok  # bare exit() is rc 0
    assert pool.stats()["tasks_completed"] == before + 3


def test_pool_rlimit_breaches_are_verdicts_not_hangs(pool):
    t0 = time.monotonic()
    # CPU spin past the rlimit -> SIGXCPU kills the task child
    r = pool.run("x = 0\nwhile True: x += 1", timeout=30.0, cpu_seconds=1)
    assert not r.ok and not r.timed_out
    # memory breach -> MemoryError verdict
    r = pool.run("b = bytearray(800 * 1024 * 1024)", memory_mb=128)
    assert not r.ok and "MemoryError" in r.output
    # fsize breach -> failure verdict
    r = pool.run(
        "f = open('big', 'wb')\nf.write(b'x' * (10 << 20))\nf.close()"
    )
    assert not r.ok
    assert time.monotonic() - t0 < 30.0


def test_pool_wall_timeout_group_kill_reaps_grandchildren(pool, tmp_path):
    """The orphan acceptance: a task that forks a long-lived grandchild
    and hangs gets process-group-killed at the wall deadline — the
    grandchild must not survive as a running process."""
    pidfile = tmp_path / "gpid"
    code = f"""
import os, time
pid = os.fork()
if pid == 0:
    with open({str(pidfile)!r}, "w") as f:
        f.write(str(os.getpid()))
    time.sleep(300)
    os._exit(0)
time.sleep(300)
"""
    r = pool.run(code, timeout=1.0)
    assert r.timed_out and not r.ok
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and not pidfile.exists():
        time.sleep(0.05)
    gpid = int(pidfile.read_text())
    # give the SIGKILL a moment to land
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and _alive_and_running(gpid):
        time.sleep(0.05)
    assert not _alive_and_running(gpid)
    # the pool replaced the killed worker: next task works
    assert pool.run("print(1)").ok


def test_pool_recycles_worker_after_n_tasks():
    p = SandboxWorkerPool(num_workers=1, recycle_after=3, default_timeout=5.0)
    try:
        # the task child's parent IS the worker: os.getppid() tracks it
        pids = [int(p.run("import os; print(os.getppid())").output) for _ in range(7)]
        # tasks 1-3 share a worker, 4-6 the next, 7 a third
        assert pids[0] == pids[1] == pids[2]
        assert pids[3] == pids[4] == pids[5]
        assert pids[2] != pids[3] and pids[5] != pids[6]
    finally:
        p.shutdown()


def test_pool_admission_bound_and_retry_after_hint():
    p = SandboxWorkerPool(
        num_workers=1, default_timeout=5.0, max_pending=2, kill_grace=0.5
    )
    try:
        done = threading.Event()
        results = []

        def slow():
            results.append(p.run("import time; time.sleep(1.2)", timeout=5.0))
            done.set()

        threads = [threading.Thread(target=slow) for _ in range(2)]
        for t in threads:
            t.start()
        time.sleep(0.3)  # both admitted (1 running + 1 queued = bound)
        with pytest.raises(PoolSaturated) as ei:
            p.run("print(1)")
        assert ei.value.retry_after > 0
        for t in threads:
            t.join(timeout=30)
    finally:
        p.shutdown()


def test_pool_async_admission_bounds_the_executor_queue():
    """Review regression: arun admits BEFORE entering the executor queue,
    so max_pending covers queued tasks too — admitting only when a thread
    picks the task up would cap pending at num_workers and let the
    executor queue grow without bound (and without a 429)."""
    p = SandboxWorkerPool(
        num_workers=1, default_timeout=5.0, max_pending=3, kill_grace=0.5
    )

    async def main():
        backlog = [
            asyncio.ensure_future(p.arun("import time; time.sleep(0.8)"))
            for _ in range(3)
        ]
        await asyncio.sleep(0.3)
        # 1 running + 2 still queued in the pool's executor: all counted
        assert p.pending() == 3
        with pytest.raises(PoolSaturated):
            await p.arun("print(1)")
        results = await asyncio.gather(*backlog)
        assert all(r.ok for r in results)
        assert p.pending() == 0

    try:
        asyncio.run(main())
    finally:
        p.shutdown()


def test_pool_cancelled_arun_stays_admitted_until_thread_finishes():
    """Review regression: a caller's wait_for giving up on arun() leaves
    the executor thread running the task — the un-admit must track the
    THREAD, not the await, or new admissions pile past max_pending while
    every slot is still occupied (and the drain-time inflight snapshot
    would omit tasks still running untrusted code)."""
    p = SandboxWorkerPool(
        num_workers=1, default_timeout=3.0, max_pending=4, kill_grace=0.5
    )

    async def main():
        t = asyncio.ensure_future(p.arun("import time; time.sleep(1.0)"))
        await asyncio.sleep(0.3)
        t.cancel()
        try:
            await t
        except asyncio.CancelledError:
            pass
        # the sandbox thread is still executing: still admitted
        assert p.pending() == 1
        deadline = time.monotonic() + 10
        while p.pending() and time.monotonic() < deadline:
            await asyncio.sleep(0.05)
        assert p.pending() == 0  # un-admitted when the thread finished

    try:
        asyncio.run(main())
    finally:
        p.shutdown()


def test_pool_retire_sweeps_daemonized_grandchildren(tmp_path):
    """Review regression: a task that daemonizes a fork and exits CLEANLY
    leaves the grandchild in the worker's process group; graceful
    retirement (recycle path) must still sweep the group."""
    p = SandboxWorkerPool(
        num_workers=1, recycle_after=1, default_timeout=5.0, kill_grace=0.5
    )
    pidfile = tmp_path / "daemon_pid"
    code = f"""
import os, time
pid = os.fork()
if pid == 0:
    os.close(0); os.close(1); os.close(2)
    with open({str(pidfile)!r}, "w") as f:
        f.write(str(os.getpid()))
    time.sleep(300)
    os._exit(0)
"""
    try:
        r = p.run(code)  # task exits cleanly; recycle_after=1 retires now
        assert r.ok
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not pidfile.exists():
            time.sleep(0.05)
        gpid = int(pidfile.read_text())
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and _alive_and_running(gpid):
            time.sleep(0.05)
        assert not _alive_and_running(gpid)
    finally:
        p.shutdown()


def test_pool_arun_rides_its_own_executor_not_the_loop_default():
    """Regression (satellite 1): wedged sandbox calls occupy pool slots
    only. The event loop's default executor stays free, the loop itself
    keeps ticking, and a subsequent fast task completes."""
    p = SandboxWorkerPool(
        num_workers=2, default_timeout=1.0, kill_grace=0.5
    )
    ticks = []

    async def heartbeat():
        while len(ticks) < 100:
            ticks.append(time.monotonic())
            await asyncio.sleep(0.01)

    async def main():
        hb = asyncio.ensure_future(heartbeat())
        wedged = [
            asyncio.ensure_future(p.arun("import time; time.sleep(300)"))
            for _ in range(2)
        ]
        fast = await p.arun("print('fast')")
        wedged_results = await asyncio.gather(*wedged)
        hb.cancel()
        return fast, wedged_results

    fast, wedged_results = asyncio.run(main())
    assert fast.ok and fast.output.strip() == "fast"
    assert all(r.timed_out for r in wedged_results)
    assert len(ticks) >= 20  # the loop never stalled on sandbox work
    p.shutdown()


def test_tool_env_never_touches_the_default_executor(tmp_path):
    """Pin the satellite fix at the source level AND behaviorally: the
    tool env executes even when the loop's default executor is fully
    saturated with hung work."""
    import ast

    import examples.tir.tool_env as tool_env_mod

    tree = ast.parse(open(tool_env_mod.__file__.rstrip("c")).read())
    offloads = [
        n
        for n in ast.walk(tree)
        if isinstance(n, ast.Call)
        and isinstance(n.func, ast.Attribute)
        and n.func.attr == "run_in_executor"
    ]
    assert not offloads, "tool env must not offload via run_in_executor"

    from concurrent.futures import ThreadPoolExecutor

    from examples.tir.tool_env import PythonToolEnv

    shutdown_default_pool()
    get_default_pool(RewardServiceConfig(num_workers=1, task_timeout=5.0))
    release = threading.Event()

    async def main():
        loop = asyncio.get_running_loop()
        tiny = ThreadPoolExecutor(max_workers=1)
        loop.set_default_executor(tiny)
        # wedge the default executor completely
        loop.run_in_executor(None, release.wait)
        env = PythonToolEnv(timeout=5.0)
        try:
            out, ok = await asyncio.wait_for(
                env.aexecute("python", {"code": "print(2 + 2)"}), timeout=15.0
            )
        finally:
            # unblock BEFORE asyncio.run tears the loop down — its
            # default-executor shutdown joins the wedged thread
            release.set()
        return out, ok

    try:
        out, ok = asyncio.run(main())
        assert ok and out.strip() == "4"
    finally:
        release.set()
        shutdown_default_pool()


# ---------------------------------------------------------------------------
# per-call sandbox (reward/sandbox.py) satellite
# ---------------------------------------------------------------------------


def test_run_sandboxed_group_kills_grandchildren_on_timeout(tmp_path):
    """Regression: subprocess.run(timeout=...) killed only the direct
    child; a forked grandchild survived the wall deadline as an orphan.
    start_new_session + killpg must reap it."""
    from areal_tpu.reward.sandbox import run_sandboxed

    pidfile = tmp_path / "gpid"
    code = f"""
import os, time
pid = os.fork()
if pid == 0:
    with open({str(pidfile)!r}, "w") as f:
        f.write(str(os.getpid()))
    time.sleep(300)
    os._exit(0)
time.sleep(300)
"""
    out, ok = run_sandboxed(code, timeout=1.0)
    assert not ok and "timed out" in out
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and not pidfile.exists():
        time.sleep(0.05)
    gpid = int(pidfile.read_text())
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and _alive_and_running(gpid):
        time.sleep(0.05)
    assert not _alive_and_running(gpid)


def test_code_verify_reward_pooled_exec_matches_per_call(pool):
    from areal_tpu.reward.sandbox import code_verify_reward, pooled_exec_fn

    completion = "answer:\n```python\nprint(int(input()) * 2)\n```"
    cases = [
        {"stdin": "2\n", "expected_stdout": "4"},
        {"stdin": "5\n", "expected_stdout": "10"},
        {"stdin": "5\n", "expected_stdout": "11"},
    ]
    per_call = code_verify_reward(None, completion, testcases=cases)
    pooled = code_verify_reward(
        None, completion, testcases=cases, exec_fn=pooled_exec_fn(pool)
    )
    assert per_call == pooled == pytest.approx(2 / 3)


# ---------------------------------------------------------------------------
# service
# ---------------------------------------------------------------------------


def _start_service(cfg, **kw):
    """Run a RewardService on a private loop thread; returns (svc, addr,
    stop)."""
    from areal_tpu.reward_service.service import RewardService

    holder = {}
    started = threading.Event()

    def run():
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        holder["loop"] = loop
        svc = RewardService(cfg, **kw)
        holder["svc"] = svc
        holder["port"] = loop.run_until_complete(svc.start("127.0.0.1", 0))
        started.set()
        loop.run_forever()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    assert started.wait(30)
    svc, loop = holder["svc"], holder["loop"]

    def stop():
        fut = asyncio.run_coroutine_threadsafe(svc.stop(), loop)
        fut.result(15)
        loop.call_soon_threadsafe(loop.stop)
        t.join(timeout=10)

    return svc, f"127.0.0.1:{holder['port']}", stop


@pytest.fixture()
def service():
    cfg = RewardServiceConfig(
        num_workers=2, task_timeout=3.0, max_pending=4
    )
    svc, addr, stop = _start_service(cfg)
    yield svc, addr, cfg
    stop()


async def _post(addr, path, payload):
    import aiohttp

    async with aiohttp.ClientSession() as s:
        async with s.post(f"http://{addr}{path}", json=payload) as resp:
            return resp.status, dict(resp.headers), await resp.json()


async def _get(addr, path):
    import aiohttp

    async with aiohttp.ClientSession() as s:
        async with s.get(f"http://{addr}{path}") as resp:
            return resp.status, await resp.text()


def test_service_run_and_batch_schema(service):
    _, addr, _ = service

    async def main():
        status, _, out = await _post(
            addr, "/run", {"code": "print(6 * 7)"}
        )
        assert status == 200 and out["ok"] and out["output"].strip() == "42"
        # reference functioncall schema: AND across testcases
        status, _, out = await _post(
            addr,
            "/run_batch",
            {
                "uid": "q0",
                "language": "PYTHON",
                "code": "print(input().strip())",
                "isFastFail": False,
                "testcases": [
                    {"input": "5\n", "expectedOutput": "5"},
                    {"input": "7\n", "expectedOutput": "8"},
                ],
            },
        )
        assert status == 200 and out["uid"] == "q0"
        assert out["success"] is False
        assert [r["success"] for r in out["results"]] == [True, False]
        # fast-fail marks the tail skipped
        status, _, out = await _post(
            addr,
            "/run_batch",
            {
                "uid": "q1",
                "code": "print('X')",
                "isFastFail": True,
                "testcases": [
                    {"input": "", "expectedOutput": "Y"},
                    {"input": "", "expectedOutput": "X"},
                ],
            },
        )
        assert out["success"] is False
        assert out["results"][1]["reason"] == "skipped (fast-fail)"
        # unsupported language is a verdict, not a 500
        status, _, out = await _post(
            addr, "/run_batch",
            {"uid": "q2", "language": "CPP", "code": "int main(){}"},
        )
        assert status == 200 and out["success"] is False

    asyncio.run(main())


def test_service_429_with_retry_after_when_saturated():
    cfg = RewardServiceConfig(num_workers=1, task_timeout=5.0, max_pending=1)
    svc, addr, stop = _start_service(cfg)
    try:

        async def main():
            import aiohttp

            async with aiohttp.ClientSession() as s:
                wedge = asyncio.ensure_future(
                    s.post(
                        f"http://{addr}/run",
                        json={"code": "import time; time.sleep(2)"},
                    )
                )
                await asyncio.sleep(0.4)
                async with s.post(
                    f"http://{addr}/run", json={"code": "print(1)"}
                ) as resp:
                    assert resp.status == 429
                    assert float(resp.headers["Retry-After"]) > 0
                async with s.post(
                    f"http://{addr}/run_batch",
                    json={
                        "uid": "b",
                        "code": "print(1)",
                        "testcases": [
                            {"input": "", "expectedOutput": "1"}
                        ] * 3,
                    },
                ) as resp:
                    assert resp.status == 429
                r = await wedge
                assert (await r.json())["ok"]
                r.release()

        asyncio.run(main())
    finally:
        stop()


def test_service_bad_request_is_400_not_500(service):
    _, addr, _ = service

    async def main():
        status, _, _ = await _post(addr, "/run", {"code": ""})
        assert status == 400

    asyncio.run(main())


def test_service_trace_header_continues_trace(service):
    """x-areal-trace propagates into per-task span events."""
    from areal_tpu.api.cli_args import TracingConfig
    from areal_tpu.utils.tracing import TRACE_HEADER, Tracer

    tracer = Tracer.from_config(TracingConfig(enabled=True, service="t"))
    cfg = RewardServiceConfig(num_workers=1, task_timeout=3.0)
    svc, addr, stop = _start_service(cfg, tracer=tracer)
    try:

        async def main():
            import aiohttp

            async with aiohttp.ClientSession() as s:
                async with s.post(
                    f"http://{addr}/run_batch",
                    json={
                        "uid": "traced",
                        "code": "print('ok')",
                        "testcases": [{"input": "", "expectedOutput": "ok"}],
                    },
                    headers={TRACE_HEADER: "11112222333344445555666677778888:aaaabbbbccccdddd"},
                ) as resp:
                    assert resp.status == 200

        asyncio.run(main())
        spans = tracer.finished_spans()
        verify = [s for s in spans if s["name"] == "reward.verify"]
        assert verify and verify[0]["trace_id"] == "11112222333344445555666677778888"
        assert any(
            e["name"] == "reward_case" for e in verify[0]["events"]
        )
    finally:
        stop()


def test_service_drain_dumps_inflight_task_set(tmp_path, monkeypatch):
    """SIGTERM-path acceptance: readiness drops, new work is refused,
    and the flight dump names the in-flight task set."""
    monkeypatch.setenv(flight_recorder.DUMP_DIR_ENV, str(tmp_path))
    flight_recorder.DEFAULT_RECORDER.reset()
    cfg = RewardServiceConfig(num_workers=1, task_timeout=8.0)
    svc, addr, stop = _start_service(cfg)
    try:

        async def main():
            import aiohttp

            async with aiohttp.ClientSession() as s:
                wedge = asyncio.ensure_future(
                    s.post(
                        f"http://{addr}/run",
                        json={
                            "code": "import time; time.sleep(4)",
                            "uid": "wedged-task",
                        },
                    )
                )
                await asyncio.sleep(0.5)
                svc.begin_drain("test")
                status, _ = await _get(addr, "/ready")
                assert status == 503
                async with s.post(
                    f"http://{addr}/run", json={"code": "print(1)"}
                ) as resp:
                    assert resp.status == 503
                r = await wedge  # in-flight work still completes
                assert (await r.json())["ok"]
                r.release()

        asyncio.run(main())
        dumps = [f for f in os.listdir(tmp_path) if f.startswith("flight_")]
        assert dumps
        snap = json.loads((tmp_path / dumps[0]).read_text())
        drains = [
            e
            for e in snap["channels"]["reward"]
            if e["kind"] == "drain"
        ]
        assert drains and "wedged-task" in drains[0]["inflight_tasks"]
    finally:
        stop()


# ---------------------------------------------------------------------------
# client: routing, chaos, breakers, fallback
# ---------------------------------------------------------------------------


def _make_client(cfg=None, addrs=None, **kw):
    from areal_tpu.reward_service.client import RewardServiceClient

    cfg = cfg or RewardServiceConfig(num_workers=1, task_timeout=3.0)
    return RewardServiceClient(cfg, addresses=addrs or [], **kw)


def test_client_least_inflight_routing_unit():
    cli = _make_client(addrs=["a:1", "b:1", "c:1"])
    cli._inflight = {"a:1": 3, "b:1": 1, "c:1": 2}
    assert cli._choose() == "b:1"
    # OPEN breaker excludes a replica outright
    cli._health.quarantine("b:1")
    assert cli._choose() == "c:1"


def test_client_no_replicas_falls_back_to_local_pool():
    pool = SandboxWorkerPool(num_workers=1, default_timeout=3.0)
    try:
        cli = _make_client(pool=pool)

        async def main():
            return await cli.aexecute_code("print('local')")

        r = asyncio.run(main())
        assert r.ok and r.output.strip() == "local"
    finally:
        pool.shutdown()


def test_client_fallback_disabled_raises():
    from areal_tpu.reward_service.client import NoServiceAvailable

    cfg = RewardServiceConfig(fallback_local=False)
    cli = _make_client(cfg=cfg)
    with pytest.raises(NoServiceAvailable):
        asyncio.run(cli.aexecute_code("print(1)"))


@pytest.mark.parametrize("action", ["http_error", "drop", "disconnect"])
def test_client_chaos_fault_opens_breaker_step_exact(action, service):
    """Chaos-injected service faults (5xx / drop-timeout / disconnect):
    call 1 fails -> CLOSED, call 2 fails -> OPEN (failure_threshold=2),
    call 3 never touches the wire (breaker) — and EVERY call still
    produces a correct verdict via the local-pool fallback."""
    from areal_tpu.utils.chaos import ChaosPolicy

    _, addr, _ = service
    chaos = ChaosPolicy()
    chaos.add_rule(endpoint="/run", action=action, times=2, status=500)
    pool = SandboxWorkerPool(num_workers=1, default_timeout=3.0)
    cfg = RewardServiceConfig(
        num_workers=1,
        task_timeout=3.0,
        request_retries=1,
        request_timeout=5.0,
        breaker=CircuitBreakerConfig(
            failure_threshold=2,
            open_cooldown_seconds=3600.0,  # no recovery inside this test
            min_window_requests=1000,
        ),
    )
    cli = _make_client(cfg=cfg, addrs=[addr], pool=pool, chaos=chaos)

    async def main():
        outs = []
        states = []
        for _ in range(3):
            outs.append(await cli.aexecute_code("print('v')"))
            states.append(cli._health.state(addr))
        await cli.close()
        return outs, states

    try:
        outs, states = asyncio.run(main())
        assert [r.ok for r in outs] == [True, True, True]
        assert [r.output.strip() for r in outs] == ["v", "v", "v"]
        assert states == ["closed", "open", "open"]
        assert chaos.injected == 2  # call 3 was routed around, not retried
    finally:
        pool.shutdown()


def test_client_breaker_recovers_via_ready_probe(service):
    """After the chaos clears, the /ready probe path (cooldown 0) moves
    the breaker OPEN -> HALF_OPEN and the next call closes it."""
    from areal_tpu.utils.chaos import ChaosPolicy

    _, addr, _ = service
    chaos = ChaosPolicy()
    chaos.add_rule(endpoint="/run", action="http_error", times=2, status=503)
    pool = SandboxWorkerPool(num_workers=1, default_timeout=3.0)
    cfg = RewardServiceConfig(
        num_workers=1,
        task_timeout=3.0,
        request_retries=1,
        breaker=CircuitBreakerConfig(
            failure_threshold=2,
            open_cooldown_seconds=0.0,
            probe_interval_seconds=0.0,
            min_window_requests=1000,
        ),
    )
    cli = _make_client(cfg=cfg, addrs=[addr], pool=pool, chaos=chaos)

    async def main():
        for _ in range(2):
            await cli.aexecute_code("print('x')")
        assert cli._health.state(addr) == "open"
        # chaos exhausted: the next call probes /ready, rejoins, and is
        # served by the SERVICE (fallback counter must not move)
        before = cli._m_fallbacks.children()
        before_n = sum(c.value for c in before.values())
        r = await cli.aexecute_code("print('recovered')")
        after_n = sum(c.value for c in cli._m_fallbacks.children().values())
        await cli.close()
        return r, cli._health.state(addr), before_n, after_n

    try:
        r, state, before_n, after_n = asyncio.run(main())
        assert r.ok and r.output.strip() == "recovered"
        assert state == "closed"
        assert after_n == before_n  # served remotely, not by fallback
    finally:
        pool.shutdown()


def test_client_verify_service_and_fallback_verdict_identical(service):
    """The same payload produces the same verdict served remotely or by
    the zero-egress local pool — both run averify_payload over the same
    pool implementation."""
    _, addr, _ = service
    payload = {
        "uid": "same",
        "code": "print(int(input()) + 1)",
        "isFastFail": False,
        "testcases": [
            {"input": "1\n", "expectedOutput": "2"},
            {"input": "2\n", "expectedOutput": "99"},
        ],
    }
    pool = SandboxWorkerPool(num_workers=1, default_timeout=3.0)
    remote_cli = _make_client(addrs=[addr], pool=pool)
    local_cli = _make_client(pool=pool)

    async def main():
        remote = await remote_cli.averify(dict(payload))
        local = await local_cli.averify(dict(payload))
        await remote_cli.close()
        return remote, local

    try:
        remote, local = asyncio.run(main())
        assert remote["success"] == local["success"] is False
        assert [r["success"] for r in remote["results"]] == [
            r["success"] for r in local["results"]
        ] == [True, False]
    finally:
        pool.shutdown()


def test_code_reward_fn_through_async_wrapper():
    """The service-plane reward fn is async; AsyncRewardWrapper awaits
    it natively and a slow reward degrades to a 0.0 verdict for THAT
    episode instead of wedging anything."""
    from areal_tpu.api.reward_api import AsyncRewardWrapper

    pool = SandboxWorkerPool(num_workers=1, default_timeout=3.0)
    cli = _make_client(pool=pool)
    reward_fn = cli.code_reward_fn(fast_fail=False)
    wrapper = AsyncRewardWrapper(reward_fn, timeout=30.0)

    completion = "```python\nprint(int(input()) * 3)\n```"
    cases = [
        {"stdin": "2\n", "expected_stdout": "6"},
        {"stdin": "3\n", "expected_stdout": "9"},
        {"stdin": "3\n", "expected_stdout": "8"},
    ]

    async def main():
        good = await wrapper(None, completion, None, None, testcases=cases)
        empty = await wrapper(None, "no code here", None, None, testcases=cases)
        # timeout discipline: a reward slower than the budget is 0.0
        async def slow_reward(*a, **k):
            await asyncio.sleep(30)

        slow = AsyncRewardWrapper(slow_reward, timeout=0.2)
        t0 = time.monotonic()
        z = await slow(None, "x", None, None)
        return good, empty, z, time.monotonic() - t0

    try:
        good, empty, z, dt = asyncio.run(main())
        assert good == pytest.approx(2 / 3)
        assert empty == 0.0
        assert z == 0.0 and dt < 5.0
    finally:
        pool.shutdown()


# ---------------------------------------------------------------------------
# remote.py retry/backoff/fallback coverage (satellite)
# ---------------------------------------------------------------------------


def test_remote_invoke_backoff_grows_and_failure_record():
    """Every attempt failing -> bounded exponential backoff between
    attempts and a schema-shaped failure record, never an exception."""
    from areal_tpu.reward.remote import RemoteSandboxConfig, batch_call

    class FailingSession:
        def __init__(self):
            self.calls = 0

        def post(self, url, json=None, timeout=None):
            self.calls += 1

            class Ctx:
                async def __aenter__(self_inner):
                    raise asyncio.TimeoutError("down")

                async def __aexit__(self_inner, *a):
                    return False

            return Ctx()

    delays = []

    async def fake_sleep(d):
        delays.append(d)

    cfg = RemoteSandboxConfig(
        url="http://sandbox/verify",
        max_retries=3,
        initial_retry_interval=0.5,
        max_retry_interval=10.0,
    )
    session = FailingSession()

    async def main():
        from areal_tpu.reward.remote import _invoke_one

        return await _invoke_one(
            session, cfg, {"uid": "u1", "code": "x"}, sleep=fake_sleep
        )

    out = asyncio.run(main())
    assert out == {
        "uid": "u1",
        "success": False,
        "results": [{"success": False, "reason": "max retries exceeded"}],
    }
    assert session.calls == 3 and len(delays) == 3
    # full backoff ladder: base*2^attempt + U(0, 0.5), capped
    assert 0.5 <= delays[0] <= 1.0
    assert 1.0 <= delays[1] <= 1.5
    assert 2.0 <= delays[2] <= 2.5
    assert batch_call  # imported symbol stays exported


def test_remote_local_fallback_uses_active_pool():
    """With the default pool up, the zero-egress fallback executes on it
    (persistent workers) instead of forking per snippet."""
    from areal_tpu.reward.remote import code_verify_batch

    shutdown_default_pool()
    pool = get_default_pool(
        RewardServiceConfig(num_workers=1, task_timeout=5.0)
    )
    try:
        before = pool.stats()["tasks_completed"]
        id2info = {
            "a": {"input_output": json.dumps({"inputs": ["3\n"], "outputs": ["3"]})},
            "b": {"input_output": json.dumps({"inputs": ["3\n"], "outputs": ["4"]})},
        }
        gens = ["```python\nprint(input().strip())\n```"] * 2
        got = code_verify_batch(id2info, gens, ["a", "b"])
        assert got == [1, 0]
        assert pool.stats()["tasks_completed"] > before
    finally:
        shutdown_default_pool()
