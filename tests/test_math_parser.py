"""Math reward parser (reference: realhf/tests/reward/test_math_reward.py)."""

import json
import os

import pytest

from areal_tpu.reward.math_parser import (
    extract_answer,
    math_equal,
    math_verify_reward,
    process_results,
)


@pytest.mark.parametrize(
    "text,expected",
    [
        ("The answer is \\boxed{42}", "42"),
        ("so \\boxed{\\frac{1}{2}} is final", "\\frac{1}{2}"),
        ("nested \\boxed{a_{1} + b}", "a_{1} + b"),
        ("earlier \\boxed{1} then \\boxed{2}", "2"),
        ("blah blah #### 18", "18"),
        ("#### 1,234", "1,234"),
        ("The final answer is 7.", "7"),
        ("we get 3 then 12 then 99", "99"),
        # last-number fallback keeps fractions intact (code-review r4)
        ("So the probability equals 3/4", "3/4"),
        # prose after the sentence period is cut; decimals survive
        ("The answer is 5. I checked it twice", "5"),
        ("The answer is 3.5", "3.5"),
        ("", None),
    ],
)
def test_extract_answer(text, expected):
    assert extract_answer(text) == expected


@pytest.mark.parametrize(
    "pred,gold,eq",
    [
        ("42", "42", True),
        ("42", "43", False),
        ("1,234", "1234", True),
        ("0.5", "\\frac{1}{2}", True),
        ("1/2", "0.5", True),
        ("\\frac{2}{4}", "1/2", True),
        ("2*x+1", "1+2x", True),
        ("x^2", "x*x", True),
        ("sqrt(4)", "2", True),
        # reference numeric_equal uses rel_tol=1e-4 (math_parser.py:486)
        ("3.14159", "3.1416", True),
        ("3.14", "3.1416", False),
        ("7 dollars", "7", True),
        ("50%", "50", True),
        ("$12", "12", True),
        (None, "1", False),
    ],
)
def test_math_equal(pred, gold, eq):
    assert math_equal(pred, gold) is eq


def test_process_results_and_reward():
    assert process_results("long reasoning ... #### 18", "#### 18") == 1
    assert process_results("\\boxed{9}", "9") == 1
    assert process_results("#### 8", "#### 18") == 0
    assert math_verify_reward(None, "ans #### 12", answer="12") == 1.0
    assert math_verify_reward(None, "ans #### 12", solution="#### 12") == 1.0
    assert math_verify_reward(None, None, answer="12") == 0.0


REF_CASES = (
    "/root/reference/realhf/tests/reward/math_answers_sample_cases.jsonl"
)


@pytest.mark.skipif(not os.path.exists(REF_CASES), reason="reference not mounted")
def test_agrees_with_reference_verifier_sample_cases():
    """Behavior parity with the reference's verify_math_solution on its OWN
    sample cases (realhf/tests/reward/test_math_reward.py labels: reward
    r = (label - 0.5) * 10)."""
    rows = [json.loads(l) for l in open(REF_CASES)]
    assert rows, "empty sample file"
    for row in rows:
        for gen, rew in zip(row["generateds"], row["rewards"], strict=True):
            want = 1 if rew > 0 else 0
            got = 0
            for sol in row["solutions"]:
                got = got or process_results(gen, sol)
            assert got == want, (row["solutions"], rew)


# ---------------------------------------------------------------------------
# Long-tail LaTeX corpus (VERDICT r3 item 10): ground-truth verdicts over
# the normalization classes the reference's 867-line strip_string +
# latex2sympy pipeline covers — spacing commands, frac shorthands, units,
# percents, word numbers, matrices, intervals/tuples, equations, rationals,
# roots, degrees, currency, scientific notation, choice letters.
# ---------------------------------------------------------------------------

LONG_TAIL = [
    # frac shorthands and nesting
    ("\\dfrac{3}{4}", "0.75", True),
    ("\\tfrac{3}{4}", "3/4", True),
    ("\\frac12", "0.5", True),
    ("\\frac1{72}", "1/72", True),
    ("\\frac{a}{b}", "a/b", True),
    ("\\frac{\\frac{1}{2}}{2}", "1/4", True),
    ("-\\frac{5}{2}", "-2.5", True),
    ("\\frac{22}{7}", "3.142857", True),
    ("\\frac{1}{3}", "0.3333", True),
    ("\\frac{1}{3}", "0.34", False),
    # spacing / markup
    ("\\left(3,\\ 4\\right)", "(3,4)", True),
    ("\\!42", "42", True),
    ("\\; 7", "7", True),
    ("\\mathbf{12}", "12", True),
    ("{8}", "8", True),
    # sqrt forms
    ("\\sqrt{16}", "4", True),
    ("\\sqrt2", "sqrt(2)", True),
    ("2\\sqrt{3}", "\\sqrt{12}", True),
    ("\\sqrt[3]{27}", "3", True),
    ("\\sqrt{8}", "2\\sqrt{2}", True),
    # pi / symbolic
    ("2\\pi", "6.2832", True),
    ("\\pi/2", "1.5708", True),
    ("x^{2}+2x+1", "(x+1)^2", True),
    ("x^{2}-1", "(x-1)(x+1)", True),
    ("x^2+1", "(x+1)^2", False),
    ("\\frac{x}{2}", "0.5x", True),
    ("2^{10}", "1024", True),
    ("e^{0}", "1", True),
    # units / currency / degrees
    ("42 \\text{ cm}", "42", True),
    ("\\$15", "15", True),
    ("90^\\circ", "90", True),
    ("90^{\\circ}", "90", True),
    ("15 \\text{ dollars}", "15", True),
    ("3 cm", "3", True),
    ("7 hours", "7", True),
    # percent triple rule (reference include_percentage)
    ("50\\%", "0.5", True),
    ("0.5", "50", True),
    ("50", "0.5", True),
    ("12.5%", "1/8", True),
    # numbers: commas, trailing zeros, leading dots
    ("1,234,567", "1234567", True),
    ("5.0", "5", True),
    (".5", "0.5", True),
    ("5.000", "5", True),
    ("1e3", "1000", True),
    ("-0", "0", True),
    # word numbers
    ("seven", "7", True),
    ("twelve", "12", True),
    # tuples / intervals / sets elementwise
    ("(1, 2)", "(1,2)", True),
    ("(1/2, 3)", "(0.5, 3)", True),
    ("[0, \\infty)", "[0,\\infty)", True),
    ("(-\\infty, 5]", "(-\\infty,5]", True),
    ("(1,2,3)", "(1,2,4)", False),
    ("\\{1, 2\\}", "{1,2}", True),
    ("(2,5)", "(5,2)", False),
    # matrices
    (
        "\\begin{pmatrix} 1 & 2 \\\\ 3 & 4 \\end{pmatrix}",
        "\\begin{pmatrix}1&2\\\\3&4\\end{pmatrix}",
        True,
    ),
    (
        "\\begin{bmatrix} 1 & 2 \\\\ 3 & 4 \\end{bmatrix}",
        "\\begin{pmatrix}1&2\\\\3&4\\end{pmatrix}",
        True,
    ),
    (
        "\\begin{pmatrix} 1/2 \\\\ 2 \\end{pmatrix}",
        "\\begin{pmatrix}0.5\\\\2\\end{pmatrix}",
        True,
    ),
    (
        "\\begin{pmatrix} 1 & 2 \\\\ 3 & 5 \\end{pmatrix}",
        "\\begin{pmatrix}1&2\\\\3&4\\end{pmatrix}",
        False,
    ),
    # equations and assignment prefixes
    ("x = 5", "5", True),
    ("y=\\frac{1}{2}", "0.5", True),
    ("x=2y+1", "2y+1=x", True),
    ("k = 3", "3", True),
    # mixed notations
    ("0.25", "\\frac{1}{4}", True),
    ("\\frac{3}{6}", "\\frac{1}{2}", True),
    ("2/3", "\\frac{2}{3}", True),
    ("1 + \\sqrt{2}", "\\sqrt{2} + 1", True),
    ("\\frac{1+\\sqrt{5}}{2}", "1.6180", True),
    # choice answers
    ("(C)", "C", True),
    ("C.", "C", True),
    ("D", "C", False),
    # text wrappers
    ("\\text{yes}", "yes", True),
    ("\\mbox{3}", "3", True),
    # negatives / signs
    ("-\\sqrt{2}", "-1.41421", True),
    ("+5", "5", True),
    # j-imaginary
    ("2j", "2i", True),
]


def test_long_tail_latex_agreement():
    wrong = []
    for pred, gold, want in LONG_TAIL:
        got = math_equal(pred, gold)
        if got is not want:
            wrong.append((pred, gold, want, got))
    rate = 1 - len(wrong) / len(LONG_TAIL)
    assert rate >= 0.99, (
        f"long-tail agreement {rate:.1%} ({len(wrong)} wrong): {wrong}"
    )


# ---------------------------------------------------------------------------
# Adversarial pass (VERDICT r4 #8): an EXTERNAL corpus not authored by the
# parser's author — the reference's MATH-500 gold answers — plus
# property-based sympy round-trips. The gold round-trip already caught one
# real bug: extract_answer applied to a bare gold mangled \frac{14}{3}
# into '3' via the last-number fallback (fixed by _extract_marked).
# ---------------------------------------------------------------------------

MATH500 = "/root/reference/evaluation/data/math_500/test.jsonl"


@pytest.mark.skipif(not os.path.exists(MATH500), reason="MATH-500 not found")
def test_math500_gold_roundtrip_agreement():
    """Every MATH-500 gold answer, boxed into a model-style solution, must
    verify against its own gold — 500 external-authored LaTeX answers
    through extraction + the full equivalence ladder."""
    rows = [json.loads(line) for line in open(MATH500)]
    assert len(rows) == 500
    fails = []
    for r in rows:
        gold = r["answer"]
        sol = f"Some reasoning.\nThe final answer is $\\boxed{{{gold}}}$."
        try:
            ok = bool(process_results(sol, gold))
        except Exception:  # noqa: BLE001 — a crash is a disagreement
            ok = False
        if not ok:
            fails.append(gold)
    rate = 1 - len(fails) / len(rows)
    assert rate >= 0.99, f"agreement {rate:.1%}; failures: {fails[:20]}"


@pytest.mark.skipif(not os.path.exists(MATH500), reason="MATH-500 not found")
def test_math500_perturbed_golds_rejected():
    """False-positive probe: numeric golds perturbed by +1 (or a digit
    swap) must NOT verify. Guards against an equivalence ladder so loose
    it matches everything."""
    import re as _re

    rows = [json.loads(line) for line in open(MATH500)]
    checked = 0
    false_pos = []
    for r in rows:
        gold = r["answer"].strip()
        if not _re.fullmatch(r"-?\d+", gold):
            continue  # perturb only clean integers (unambiguous mutation)
        wrong = str(int(gold) + 1)
        sol = f"The final answer is $\\boxed{{{wrong}}}$."
        checked += 1
        if process_results(sol, gold):
            false_pos.append((gold, wrong))
    assert checked >= 100, f"only {checked} integer golds found"
    assert not false_pos, false_pos


def test_sympy_roundtrip_property():
    """Property-based: a value rendered two different ways (sympy.latex vs
    plain str / evalf) must verify as equal, and values that differ by a
    nonzero delta must not. Seeded generator (hypothesis's sympy strategies
    would be overkill; determinism keeps CI stable)."""
    import sympy
    from sympy import Rational, latex, sqrt

    import numpy as np

    rng = np.random.default_rng(0)
    agree_fail, reject_fail = [], []
    for _ in range(60):
        kind = rng.integers(0, 4)
        if kind == 0:  # rational
            p, q = int(rng.integers(-40, 40)), int(rng.integers(1, 12))
            val = Rational(p, q)
        elif kind == 1:  # integer
            val = sympy.Integer(int(rng.integers(-1000, 1000)))
        elif kind == 2:  # k*sqrt(n)
            k, n = int(rng.integers(1, 9)), int(rng.integers(2, 30))
            val = k * sqrt(n)
        else:  # rational multiple of pi
            p, q = int(rng.integers(1, 12)), int(rng.integers(1, 6))
            val = Rational(p, q) * sympy.pi
        a = latex(val)
        b = sympy.sstr(val)  # e.g. 3*sqrt(2)/2, pi/3
        if not math_equal(a, b):
            agree_fail.append((a, b))
        # a float rendering within tolerance must also agree
        if val.is_real and not math_equal(a, str(sympy.N(val, 10))):
            agree_fail.append((a, "N"))
        # perturbed value must be rejected
        wrong = latex(val + Rational(1, 3))
        if math_equal(a, wrong):
            reject_fail.append((a, wrong))
    assert not agree_fail, agree_fail[:10]
    assert not reject_fail, reject_fail[:10]


def test_integer_gold_exactness():
    """Review findings r5: the rel-tol ladder must not apply to
    integer-valued golds, and integer compares must be arbitrary
    precision (floats collapse above 2^53)."""
    assert not math_equal("13536", "13535")
    assert not math_equal("13535.5", "13535")  # decimal near-integer
    assert not math_equal("13535.9", "13535")
    assert math_equal("13535", "13535")
    assert math_equal("13535.0", "13535")
    # above 2^53: adjacent ints are distinct doubles no more
    assert not math_equal("9007199254740993", "9007199254740992")
    assert math_equal("9007199254740993", "9007199254740993")
    # percentage triple survives the tightening
    assert math_equal("0.5", "50")
    assert math_equal("5000", "50")
    # non-integer golds keep the reference rel-tol
    assert math_equal("0.33333", "1/3")
