"""Math reward parser (reference: realhf/tests/reward/test_math_reward.py)."""

import json
import os

import pytest

from areal_tpu.reward.math_parser import (
    extract_answer,
    math_equal,
    math_verify_reward,
    process_results,
)


@pytest.mark.parametrize(
    "text,expected",
    [
        ("The answer is \\boxed{42}", "42"),
        ("so \\boxed{\\frac{1}{2}} is final", "\\frac{1}{2}"),
        ("nested \\boxed{a_{1} + b}", "a_{1} + b"),
        ("earlier \\boxed{1} then \\boxed{2}", "2"),
        ("blah blah #### 18", "18"),
        ("#### 1,234", "1,234"),
        ("The final answer is 7.", "7"),
        ("we get 3 then 12 then 99", "99"),
        ("", None),
    ],
)
def test_extract_answer(text, expected):
    assert extract_answer(text) == expected


@pytest.mark.parametrize(
    "pred,gold,eq",
    [
        ("42", "42", True),
        ("42", "43", False),
        ("1,234", "1234", True),
        ("0.5", "\\frac{1}{2}", True),
        ("1/2", "0.5", True),
        ("\\frac{2}{4}", "1/2", True),
        ("2*x+1", "1+2x", True),
        ("x^2", "x*x", True),
        ("sqrt(4)", "2", True),
        ("3.14159", "3.1416", False),
        ("7 dollars", "7", True),
        ("50%", "50", True),
        ("$12", "12", True),
        (None, "1", False),
    ],
)
def test_math_equal(pred, gold, eq):
    assert math_equal(pred, gold) is eq


def test_process_results_and_reward():
    assert process_results("long reasoning ... #### 18", "#### 18") == 1
    assert process_results("\\boxed{9}", "9") == 1
    assert process_results("#### 8", "#### 18") == 0
    assert math_verify_reward(None, "ans #### 12", answer="12") == 1.0
    assert math_verify_reward(None, "ans #### 12", solution="#### 12") == 1.0
    assert math_verify_reward(None, None, answer="12") == 0.0


REF_CASES = (
    "/root/reference/realhf/tests/reward/math_answers_sample_cases.jsonl"
)


@pytest.mark.skipif(not os.path.exists(REF_CASES), reason="reference not mounted")
def test_agrees_with_reference_verifier_sample_cases():
    """Behavior parity with the reference's verify_math_solution on its OWN
    sample cases (realhf/tests/reward/test_math_reward.py labels: reward
    r = (label - 0.5) * 10)."""
    rows = [json.loads(l) for l in open(REF_CASES)]
    assert rows, "empty sample file"
    for row in rows:
        for gen, rew in zip(row["generateds"], row["rewards"], strict=True):
            want = 1 if rew > 0 else 0
            got = 0
            for sol in row["solutions"]:
                got = got or process_results(gen, sol)
            assert got == want, (row["solutions"], rew)
