"""Per-process worker for the controller-mode e2e test.

Each process: one virtual CPU device, joins the jax.distributed dp=N mesh,
hosts a tiny TPUPPOActor behind EngineRPCServer, writes its port to
<outdir>/port<pid>, serves until the controller writes <outdir>/stop.

Usage: python controller_worker_driver.py <coordinator> <nprocs> <pid> <outdir>
"""

import json
import os
import sys
import time


def main():
    coordinator, nprocs, pid, outdir = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4],
    )
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=1"
    ).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")

    from areal_tpu.parallel import distributed

    distributed.initialize(
        coordinator_address=coordinator, num_processes=nprocs, process_id=pid
    )

    import numpy as np

    from areal_tpu.api.alloc_mode import ParallelStrategy
    from areal_tpu.api.cli_args import OptimizerConfig, PPOActorConfig
    from areal_tpu.controller.worker import serve
    from areal_tpu.engine.ppo.actor import TPUPPOActor
    from areal_tpu.models.config import tiny_config

    cfg = PPOActorConfig(
        path="",
        init_from_scratch=True,
        optimizer=OptimizerConfig(lr=1e-3),
        group_size=2,
        ppo_n_minibatches=1,
        recompute_logprob=True,
        use_decoupled_loss=True,
    )
    cfg.backend.param_dtype = "float32"
    cfg.backend.pad_mb_to_multiple = 32
    actor = TPUPPOActor(cfg)
    actor.create_process_group(ParallelStrategy(dp=nprocs))
    actor.initialize(None, None, model_config=tiny_config(), seed=7)

    serve(actor, "127.0.0.1", 0, os.path.join(outdir, f"port{pid}"))

    stop = os.path.join(outdir, "stop")
    deadline = time.time() + 570
    while not os.path.exists(stop) and time.time() < deadline:
        time.sleep(0.2)

    # post-run evidence for the test: params must be IDENTICAL across
    # workers (the mesh's grad psum, not RPC, keeps them in sync). The
    # embed may be fsdp-sharded across processes — allgather to host
    # (collective: every worker joins).
    from areal_tpu.parallel.distributed import gather_host_values

    np.save(
        os.path.join(outdir, f"embed{pid}.npy"),
        np.asarray(gather_host_values(actor.params["embed"])),
    )
    with open(os.path.join(outdir, f"done{pid}.json"), "w") as f:
        json.dump({"version": actor.get_version()}, f)


if __name__ == "__main__":
    main()
