"""LoRA: adapter-only training with frozen base, merged serving/export,
adapter checkpoint round trip (VERDICT r1 missing #7; reference:
examples/lora/gsm8k_grpo_lora.py + sglang_remote.py:82-106 hot-swap)."""

import jax
import numpy as np
import pytest

from areal_tpu.api.cli_args import LoRAConfig, OptimizerConfig, TrainEngineConfig
from areal_tpu.api.io_struct import SaveLoadMeta
from areal_tpu.engine.sft.lm_engine import TPULMEngine
from areal_tpu.models.config import tiny_config


def _cfg(**over):
    cfg = TrainEngineConfig(
        path="",
        init_from_scratch=True,
        optimizer=OptimizerConfig(lr=5e-3),
        lora=LoRAConfig(rank=4, alpha=8.0),
    )
    cfg.backend.param_dtype = "float32"
    cfg.backend.pad_mb_to_multiple = 32
    for k, v in over.items():
        setattr(cfg, k, v)
    return cfg


def _data(seed=0):
    rng = np.random.default_rng(seed)
    data = dict(
        input_ids=rng.integers(1, 128, size=(4, 16)).astype(np.int32),
        attention_mask=np.ones((4, 16), np.int32),
        loss_mask=np.ones((4, 16), np.int32),
    )
    data["loss_mask"][:, 0] = 0
    return data


def test_lora_trains_adapters_only():
    eng = TPULMEngine(_cfg())
    eng.initialize(None, None, model_config=tiny_config(), seed=0)
    base_before = jax.device_get(eng.params["layers"]["wq"])
    lora_b_before = jax.device_get(eng.lora_params["layers"]["wq_b"])
    assert np.all(np.asarray(lora_b_before) == 0)  # identity adapter at init

    data = _data()
    losses = [eng.train_lm(data)["loss"] for _ in range(6)]
    assert losses[-1] < losses[0], losses

    base_after = jax.device_get(eng.params["layers"]["wq"])
    lora_b_after = jax.device_get(eng.lora_params["layers"]["wq_b"])
    np.testing.assert_array_equal(
        np.asarray(base_before), np.asarray(base_after)
    )  # base frozen
    assert not np.allclose(np.asarray(lora_b_after), 0)  # adapters moved
    eng.destroy()


def test_lora_effective_params_used_for_scoring_and_export(tmp_path):
    eng = TPULMEngine(_cfg())
    eng.initialize(None, None, model_config=tiny_config(), seed=1)
    data = _data(1)
    for _ in range(4):
        eng.train_lm(data)

    eff = eng.effective_params()
    base = eng.params
    assert not np.allclose(
        np.asarray(jax.device_get(eff["layers"]["wq"])),
        np.asarray(jax.device_get(base["layers"]["wq"])),
    )

    # merged weights flow through the weight-update chunk walk
    names = set()
    for chunk in eng._weight_chunks(1):
        names.update(chunk)
        for k, v in chunk.items():
            if k == "layers.wq":
                np.testing.assert_allclose(
                    v,
                    np.asarray(jax.device_get(eff["layers"]["wq"])),
                    rtol=1e-6,
                )
    assert "layers.wq" in names
    eng.destroy()


def test_lora_checkpoint_roundtrip_resumes_exactly(tmp_path):
    eng = TPULMEngine(_cfg())
    eng.initialize(None, None, model_config=tiny_config(), seed=2)
    data = _data(2)
    for _ in range(3):
        eng.train_lm(data)
    eng.save(SaveLoadMeta(path=str(tmp_path), weight_format="hf", with_optim=True))
    lora_ref = jax.device_get(eng.lora_params["layers"]["wq_a"])
    eng.destroy()

    eng2 = TPULMEngine(_cfg(path=str(tmp_path), init_from_scratch=False))
    eng2.initialize(None, None, model_config=tiny_config(), seed=9)
    eng2.load(SaveLoadMeta(path=str(tmp_path), weight_format="hf", with_optim=True))
    np.testing.assert_allclose(
        np.asarray(jax.device_get(eng2.lora_params["layers"]["wq_a"])),
        np.asarray(lora_ref),
        rtol=1e-6,
    )
    # training continues without error after resume
    stats = eng2.train_lm(data)
    assert np.isfinite(stats["loss"])
    eng2.destroy()


def test_lora_unknown_target_raises():
    from areal_tpu.models.lora import init_lora_params

    with pytest.raises(ValueError, match="unknown LoRA target"):
        init_lora_params(
            tiny_config(),
            LoRAConfig(target_modules=["bogus_proj"]),
            jax.random.PRNGKey(0),
        )


def test_lora_orbax_roundtrip(tmp_path):
    eng = TPULMEngine(_cfg())
    eng.initialize(None, None, model_config=tiny_config(), seed=3)
    data = _data(3)
    eng.train_lm(data)
    eng.save(SaveLoadMeta(path=str(tmp_path / "ck"), weight_format="orbax", with_optim=True))
    ref = np.asarray(jax.device_get(eng.lora_params["layers"]["wq_b"]))
    eng.destroy()

    eng2 = TPULMEngine(_cfg())
    eng2.initialize(None, None, model_config=tiny_config(), seed=8)
    eng2.load(SaveLoadMeta(path=str(tmp_path / "ck"), weight_format="orbax", with_optim=True))
    np.testing.assert_allclose(
        np.asarray(jax.device_get(eng2.lora_params["layers"]["wq_b"])), ref, rtol=1e-6
    )
    eng2.destroy()
