"""LoRA: adapter-only training with frozen base, merged serving/export,
adapter checkpoint round trip (VERDICT r1 missing #7; reference:
examples/lora/gsm8k_grpo_lora.py + sglang_remote.py:82-106 hot-swap)."""

import jax
import numpy as np
import pytest

from areal_tpu.api.cli_args import LoRAConfig, OptimizerConfig, TrainEngineConfig
from areal_tpu.api.io_struct import SaveLoadMeta
from areal_tpu.engine.sft.lm_engine import TPULMEngine
from areal_tpu.models.config import tiny_config


def _cfg(**over):
    cfg = TrainEngineConfig(
        path="",
        init_from_scratch=True,
        optimizer=OptimizerConfig(lr=5e-3),
        lora=LoRAConfig(rank=4, alpha=8.0),
    )
    cfg.backend.param_dtype = "float32"
    cfg.backend.pad_mb_to_multiple = 32
    for k, v in over.items():
        setattr(cfg, k, v)
    return cfg


def _data(seed=0):
    rng = np.random.default_rng(seed)
    data = dict(
        input_ids=rng.integers(1, 128, size=(4, 16)).astype(np.int32),
        attention_mask=np.ones((4, 16), np.int32),
        loss_mask=np.ones((4, 16), np.int32),
    )
    data["loss_mask"][:, 0] = 0
    return data


def test_lora_trains_adapters_only():
    eng = TPULMEngine(_cfg())
    eng.initialize(None, None, model_config=tiny_config(), seed=0)
    base_before = jax.device_get(eng.params["layers"]["wq"])
    lora_b_before = jax.device_get(eng.lora_params["layers"]["wq_b"])
    assert np.all(np.asarray(lora_b_before) == 0)  # identity adapter at init

    data = _data()
    losses = [eng.train_lm(data)["loss"] for _ in range(6)]
    assert losses[-1] < losses[0], losses

    base_after = jax.device_get(eng.params["layers"]["wq"])
    lora_b_after = jax.device_get(eng.lora_params["layers"]["wq_b"])
    np.testing.assert_array_equal(
        np.asarray(base_before), np.asarray(base_after)
    )  # base frozen
    assert not np.allclose(np.asarray(lora_b_after), 0)  # adapters moved
    eng.destroy()


def test_lora_effective_params_used_for_scoring_and_export(tmp_path):
    eng = TPULMEngine(_cfg())
    eng.initialize(None, None, model_config=tiny_config(), seed=1)
    data = _data(1)
    for _ in range(4):
        eng.train_lm(data)

    eff = eng.effective_params()
    base = eng.params
    assert not np.allclose(
        np.asarray(jax.device_get(eff["layers"]["wq"])),
        np.asarray(jax.device_get(base["layers"]["wq"])),
    )

    # merged weights flow through the weight-update chunk walk
    names = set()
    for chunk in eng._weight_chunks(1):
        names.update(chunk)
        for k, v in chunk.items():
            if k == "layers.wq":
                np.testing.assert_allclose(
                    v,
                    np.asarray(jax.device_get(eff["layers"]["wq"])),
                    rtol=1e-6,
                )
    assert "layers.wq" in names
    eng.destroy()


def test_lora_checkpoint_roundtrip_resumes_exactly(tmp_path):
    eng = TPULMEngine(_cfg())
    eng.initialize(None, None, model_config=tiny_config(), seed=2)
    data = _data(2)
    for _ in range(3):
        eng.train_lm(data)
    eng.save(SaveLoadMeta(path=str(tmp_path), weight_format="hf", with_optim=True))
    lora_ref = jax.device_get(eng.lora_params["layers"]["wq_a"])
    eng.destroy()

    eng2 = TPULMEngine(_cfg(path=str(tmp_path), init_from_scratch=False))
    eng2.initialize(None, None, model_config=tiny_config(), seed=9)
    eng2.load(SaveLoadMeta(path=str(tmp_path), weight_format="hf", with_optim=True))
    np.testing.assert_allclose(
        np.asarray(jax.device_get(eng2.lora_params["layers"]["wq_a"])),
        np.asarray(lora_ref),
        rtol=1e-6,
    )
    # training continues without error after resume
    stats = eng2.train_lm(data)
    assert np.isfinite(stats["loss"])
    eng2.destroy()


def test_lora_unknown_target_raises():
    from areal_tpu.models.lora import init_lora_params

    with pytest.raises(ValueError, match="unknown LoRA target"):
        init_lora_params(
            tiny_config(),
            LoRAConfig(target_modules=["bogus_proj"]),
            jax.random.PRNGKey(0),
        )


def test_lora_orbax_roundtrip(tmp_path):
    eng = TPULMEngine(_cfg())
    eng.initialize(None, None, model_config=tiny_config(), seed=3)
    data = _data(3)
    eng.train_lm(data)
    eng.save(SaveLoadMeta(path=str(tmp_path / "ck"), weight_format="orbax", with_optim=True))
    ref = np.asarray(jax.device_get(eng.lora_params["layers"]["wq_b"]))
    eng.destroy()

    eng2 = TPULMEngine(_cfg())
    eng2.initialize(None, None, model_config=tiny_config(), seed=8)
    eng2.load(SaveLoadMeta(path=str(tmp_path / "ck"), weight_format="orbax", with_optim=True))
    np.testing.assert_allclose(
        np.asarray(jax.device_get(eng2.lora_params["layers"]["wq_b"])), ref, rtol=1e-6
    )
    eng2.destroy()


# ---------------------------------------------------------------------------
# Adapter-native serving (round-2 verdict item 3): an adapter-only push must
# produce logits identical to pushing the fully merged weights, ship far
# fewer bytes, and merge against the retained BASE on every update.
# ---------------------------------------------------------------------------


def _gen_engine(cfg, params):
    from areal_tpu.api.cli_args import JaxGenConfig
    from areal_tpu.inference.engine import GenerationEngine

    eng = GenerationEngine(
        JaxGenConfig(
            max_batch_size=4,
            max_seq_len=256,
            prefill_chunk=64,
            decode_steps_per_call=4,
            dtype="float32",
        ),
        model_config=cfg,
        params=params,
    )
    eng.start()
    return eng


def _greedy(eng, prompt, n=6, rid="r"):
    import threading

    from areal_tpu.api.cli_args import GenerationHyperparameters

    done = threading.Event()
    out = {}

    def cb(r):
        out["r"] = r
        done.set()

    eng.submit(
        rid, prompt,
        GenerationHyperparameters(max_new_tokens=n, greedy=True), cb,
    )
    assert done.wait(120), "generation timed out"
    return out["r"]


def _named_adapters(lora_params):
    return {
        f"layers.{k}": np.asarray(jax.device_get(v))
        for k, v in lora_params["layers"].items()
    }


def test_adapter_update_matches_merged_weights():
    from areal_tpu.models.lm import init_params
    from areal_tpu.models.lora import init_lora_params, merge_lora

    cfg = tiny_config()
    lcfg = LoRAConfig(rank=4, alpha=8.0)
    base = init_params(cfg, jax.random.PRNGKey(0), np.float32)
    # a non-trivial adapter: B must be nonzero for the update to matter
    lora = init_lora_params(cfg, lcfg, jax.random.PRNGKey(1), np.float32)
    lora["layers"] = {
        k: (
            jax.random.normal(jax.random.PRNGKey(i), v.shape) * 0.05
            if k.endswith("_b") else v
        )
        for i, (k, v) in enumerate(sorted(lora["layers"].items()))
    }
    merged = merge_lora(base, lora, lcfg)
    scale = lcfg.alpha / lcfg.rank
    prompt = [5, 9, 3, 7, 2]

    eng_merged = _gen_engine(cfg, merged)
    try:
        want = _greedy(eng_merged, prompt)
    finally:
        eng_merged.stop()

    eng = _gen_engine(cfg, base)
    try:
        before = _greedy(eng, prompt, rid="r0")
        eng.update_lora_from_named_arrays(_named_adapters(lora), scale, 3)
        got = _greedy(eng, prompt, rid="r1")
        assert eng.get_version() == 3
        assert got.output_tokens == want.output_tokens
        np.testing.assert_allclose(
            got.output_logprobs, want.output_logprobs, rtol=1e-5, atol=1e-6
        )
        # the adapter actually changed the outputs
        assert (
            before.output_tokens != got.output_tokens
            or before.output_logprobs != got.output_logprobs
        )

        # second adapter must merge against the retained BASE, not the
        # previously merged params
        lora2 = init_lora_params(cfg, lcfg, jax.random.PRNGKey(7), np.float32)
        lora2["layers"] = {
            k: (
                jax.random.normal(jax.random.PRNGKey(100 + i), v.shape) * 0.05
                if k.endswith("_b") else v
            )
            for i, (k, v) in enumerate(sorted(lora2["layers"].items()))
        }
        eng.update_lora_from_named_arrays(_named_adapters(lora2), scale, 4)
        merged2 = merge_lora(base, lora2, lcfg)
        np.testing.assert_allclose(
            np.asarray(jax.device_get(eng.params["layers"]["wq"])),
            np.asarray(jax.device_get(merged2["layers"]["wq"])),
            rtol=1e-5, atol=1e-6,
        )
    finally:
        eng.stop()


def test_adapter_http_endpoint_and_payload_size():
    import asyncio
    import threading

    import aiohttp
    from safetensors.numpy import save as st_save

    from areal_tpu.inference.server import GenerationServer
    from areal_tpu.models.lm import init_params
    from areal_tpu.models.lora import init_lora_params, merge_lora
    from areal_tpu.utils.http import arequest_with_retry

    cfg = tiny_config()
    lcfg = LoRAConfig(rank=4, alpha=8.0)
    base = init_params(cfg, jax.random.PRNGKey(0), np.float32)
    lora = init_lora_params(cfg, lcfg, jax.random.PRNGKey(1), np.float32)
    lora["layers"] = {
        k: (np.full(v.shape, 0.02, np.float32) if k.endswith("_b") else v)
        for k, v in lora["layers"].items()
    }

    eng = _gen_engine(cfg, base)
    server = GenerationServer(eng)
    loop = asyncio.new_event_loop()
    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()
    try:
        port = asyncio.run_coroutine_threadsafe(
            server.start("127.0.0.1", 0), loop
        ).result(timeout=60)

        adapter_blob = st_save(
            {k: np.ascontiguousarray(v) for k, v in _named_adapters(lora).items()}
        )
        full_blob = st_save(
            {
                f"layers.{k}": np.ascontiguousarray(jax.device_get(v))
                for k, v in merge_lora(base, lora, lcfg)["layers"].items()
            }
        )
        # the point of adapter-native serving: the sync payload is tiny
        assert len(adapter_blob) * 5 < len(full_blob), (
            len(adapter_blob), len(full_blob),
        )

        scale = lcfg.alpha / lcfg.rank

        async def _push():
            async with aiohttp.ClientSession() as session:
                return await arequest_with_retry(
                    session,
                    f"http://127.0.0.1:{port}/update_lora_weights"
                    f"?version=2&scale={scale}",
                    data=adapter_blob,
                )

        res = asyncio.run(_push())
        assert res["success"], res
        assert res["weight_version"] == 2
        np.testing.assert_allclose(
            np.asarray(jax.device_get(eng.params["layers"]["wq"])),
            np.asarray(
                jax.device_get(merge_lora(base, lora, lcfg)["layers"]["wq"])
            ),
            rtol=1e-5, atol=1e-6,
        )
    finally:
        asyncio.run_coroutine_threadsafe(server.stop(), loop).result(timeout=30)
        loop.call_soon_threadsafe(loop.stop)


def test_lora_meta_drives_adapter_push_colocated():
    """Full chain: LoRA trainer -> WeightUpdateMeta.from_lora ->
    LocalInfEngine -> GenerationEngine serves base + trained adapters."""
    from areal_tpu.api.cli_args import InferenceEngineConfig, JaxGenConfig
    from areal_tpu.api.io_struct import WeightUpdateMeta
    from areal_tpu.engine.local_inf import LocalInfEngine

    model_cfg = tiny_config()
    eng = TPULMEngine(_cfg())
    eng.initialize(None, None, model_config=model_cfg, seed=0)
    for _ in range(3):
        eng.train_lm(_data())

    inf = LocalInfEngine(
        InferenceEngineConfig(max_concurrent_rollouts=2, consumer_batch_size=2),
        JaxGenConfig(
            max_batch_size=2, max_seq_len=128, prefill_chunk=32,
            decode_steps_per_call=2, dtype="float32",
        ),
        model_config=model_cfg,
        params=eng.params,  # serving starts from the BASE weights
    )
    inf.initialize(None, train_data_parallel_size=1)
    try:
        eng.connect_engine(inf, WeightUpdateMeta.from_lora())
        eng.update_weights()
        assert inf.get_version() == 1
        eff = eng.effective_params()
        np.testing.assert_allclose(
            np.asarray(jax.device_get(inf.engine.params["layers"]["wq"])),
            np.asarray(jax.device_get(eff["layers"]["wq"])),
            rtol=1e-5, atol=1e-6,
        )
    finally:
        inf.destroy()
        eng.destroy()
