"""Mesh + param sharding rules on the 8-virtual-device CPU platform."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from areal_tpu.api.alloc_mode import ParallelStrategy
from areal_tpu.models import lm
from areal_tpu.models.config import tiny_config
from areal_tpu.parallel.mesh import make_mesh
from areal_tpu.utils import jax_compat
from areal_tpu.parallel.sharding import param_shardings
from areal_tpu.utils.data import (
    positions_from_cu_seqlens,
    segment_ids_from_cu_seqlens,
)


def test_make_mesh_shapes(cpu_devices):
    mesh = make_mesh(ParallelStrategy(dp=2, tp=2, cp=2))
    assert mesh.shape == {"pp": 1, "dp": 2, "cp": 2, "tp": 2}
    with pytest.raises(ValueError):
        make_mesh(ParallelStrategy(dp=16))


def test_param_shardings_cover_tree(cpu_devices):
    mesh = make_mesh(ParallelStrategy(dp=2, tp=2, cp=2))
    cfg = tiny_config(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_hidden_layers=2
    )
    params = lm.init_params(cfg, jax.random.PRNGKey(0))
    shardings = param_shardings(mesh, params, fsdp=True)
    flat_p = jax.tree_util.tree_leaves_with_path(params)
    flat_s = jax.tree_util.tree_leaves(shardings)
    assert len(flat_p) == len(flat_s)
    # place every leaf with its sharding — raises if specs don't divide
    placed = jax.device_put(params, shardings)
    # wq head dim (32) must be tp-sharded
    wq_spec = shardings["layers"]["wq"].spec
    assert wq_spec[-1] == "tp"
    # embed vocab-sharded
    assert shardings["embed"].spec[0] == "tp"
    jax.block_until_ready(placed)


def test_sharded_forward_matches_single_device(cpu_devices):
    """Forward under a dp×cp×tp mesh must equal single-device forward."""
    cfg = tiny_config()
    params = lm.init_params(cfg, jax.random.PRNGKey(1), dtype=jnp.float32)
    lens = [16, 16]
    rng = np.random.default_rng(0)
    flat = rng.integers(1, cfg.vocab_size, size=sum(lens)).astype(np.int32)
    cu = np.concatenate([[0], np.cumsum(lens)]).astype(np.int32)
    pos = positions_from_cu_seqlens(cu)
    seg = segment_ids_from_cu_seqlens(cu)

    ref = np.asarray(
        lm.forward_packed(
            params, cfg, jnp.asarray(flat), jnp.asarray(pos), jnp.asarray(seg)
        )
    )

    mesh = make_mesh(ParallelStrategy(dp=2, tp=2, cp=2))
    shardings = param_shardings(mesh, params, fsdp=True)
    sharded_params = jax.device_put(params, shardings)

    @jax.jit
    def fwd(p, ids, pos, seg):
        return lm.forward_packed(p, cfg, ids, pos, seg)

    with jax_compat.set_mesh(mesh):
        out = np.asarray(fwd(sharded_params, jnp.asarray(flat), jnp.asarray(pos), jnp.asarray(seg)))
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


def test_attn_spec_for_mesh_rules():
    """Shared dispatch rule (train + inference engines both call this)."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from areal_tpu.models.config import tiny_config
    from areal_tpu.ops.attention import AttnSpec

    cfg = tiny_config(num_attention_heads=4, num_key_value_heads=2)
    devs = np.asarray(jax.devices()[:8])

    # tp=2 divides both head counts -> head-sharded, token ring over dp,cp
    mesh = Mesh(devs.reshape(1, 2, 2, 2), ("pp", "dp", "cp", "tp"))
    s = AttnSpec.for_mesh(mesh, cfg)
    assert s.head_axis == "tp" and s.token_axes == ("dp", "cp")

    # tp=4 does not divide kv heads -> forced einsum, heads replicated
    mesh = Mesh(devs.reshape(1, 2, 1, 4), ("pp", "dp", "cp", "tp"))
    s = AttnSpec.for_mesh(mesh, cfg)
    assert s.head_axis is None and s.impl == "xla"
    assert s.token_axes == ("dp", "cp")  # ring still on

    # single-extent mesh -> plain local spec, no mesh reference
    mesh = Mesh(devs[:1].reshape(1, 1, 1, 1), ("pp", "dp", "cp", "tp"))
    s = AttnSpec.for_mesh(mesh, cfg)
    assert s.mesh is None


def test_live_param_reshard_across_topologies():
    """Param realloc between topologies (reference: realhf param realloc /
    VERDICT r3 §2.5 partial): under GSPMD a live topology->topology
    re-shard IS one device_put with the target NamedShardings — no
    interval machinery, no host roundtrip. d4t2 training layout ->
    d1t2p4-style layout and back must preserve every leaf bit-exactly."""
    import numpy as np

    from areal_tpu.api.alloc_mode import ParallelStrategy
    from areal_tpu.models.config import tiny_config
    from areal_tpu.models.lm import init_params
    from areal_tpu.parallel.mesh import make_mesh
    from areal_tpu.parallel.sharding import param_shardings

    cfg = tiny_config(num_hidden_layers=4)
    mesh_a = make_mesh(ParallelStrategy(dp=4, tp=2))
    mesh_b = make_mesh(ParallelStrategy(tp=2, pp=4))
    params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    host = jax.tree.map(np.asarray, params)

    p_a = jax.device_put(params, param_shardings(mesh_a, params, fsdp=True))
    # live reshard A -> B (fsdp layout -> pp-stacked layout)
    p_b = jax.device_put(p_a, param_shardings(mesh_b, params, fsdp=False))
    # and back
    p_a2 = jax.device_put(p_b, param_shardings(mesh_a, params, fsdp=True))

    for path, leaf in jax.tree_util.tree_leaves_with_path(p_b):
        np.testing.assert_array_equal(
            np.asarray(leaf),
            dict(jax.tree_util.tree_leaves_with_path(host))[path],
            err_msg=str(path),
        )
    for path, leaf in jax.tree_util.tree_leaves_with_path(p_a2):
        np.testing.assert_array_equal(
            np.asarray(leaf),
            dict(jax.tree_util.tree_leaves_with_path(host))[path],
            err_msg=str(path),
        )
