"""The bench harness itself must work: a broken bench.py costs an entire
round's only TPU window (rounds 1 and 2 both lost their bench to harness +
tunnel failures).

Covers: the GRPO step bench end to end in smoke (tiny-model CPU) mode, and
bench.py's subprocess probe plumbing (parse, timeout handling, partial
records) without touching any real backend.
"""

import json
import subprocess
import sys

import pytest


def test_grpo_step_bench_smoke():
    from bench_grpo import grpo_step_bench

    res = grpo_step_bench(
        n_prompts=2, group_size=2, prompt_len=8, new_tokens=4, steps=1,
        smoke=True,
    )
    assert res["step_sec"] > 0
    assert res["sync_step_sec"] > 0
    assert 0.0 <= res["overlap_fraction"] <= 1.0
    assert set(res["phase_breakdown"]) == {
        "rollout_s", "logp_s", "adv_s", "train_s", "push_s",
    }


def test_bench_probe_child_parses_on_cpu(tmp_path):
    """--probe-child emits one parseable JSON line (CPU backend here)."""
    import os

    env = dict(os.environ)
    # AREAL_PLATFORM drives jax.config.update in the child — env-var-only
    # JAX_PLATFORMS doesn't defeat the force-registered TPU plugin
    env["JAX_PLATFORMS"] = "cpu"
    env["AREAL_PLATFORM"] = "cpu"
    r = subprocess.run(
        [sys.executable, "bench.py", "--probe-child", "{}"],
        capture_output=True, text=True, timeout=300, cwd="/root/repo",
        env=env,
    )
    assert r.returncode == 0, r.stderr[-1500:]
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["n"] >= 1
    assert rec["t_init"] >= 0


def test_bench_emit_writes_partial(tmp_path, monkeypatch):
    import bench

    monkeypatch.setattr(bench, "PARTIAL_PATH", str(tmp_path / "p.jsonl"))
    bench.emit({"metric": "x", "value": 1})
    bench.emit({"metric": "y", "value": 2})
    lines = (tmp_path / "p.jsonl").read_text().strip().splitlines()
    assert [json.loads(ln)["metric"] for ln in lines] == ["x", "y"]


def test_probe_backend_gives_up_within_budget(monkeypatch):
    """A permanently wedged tunnel must exhaust the wall budget and raise
    (driver then records the error line) — not hang."""
    import bench

    calls = []

    def fake_run_child(kind, att, timeout):
        calls.append(timeout)
        raise subprocess.TimeoutExpired(cmd="probe", timeout=timeout)

    monkeypatch.setattr(bench, "_run_child", fake_run_child)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    deadline = bench.time.time() + 0.05  # nearly-spent budget
    with pytest.raises(RuntimeError, match="unavailable"):
        bench.probe_backend(deadline)
    assert len(calls) == 0  # budget below the 90s floor -> no attempt

    # with budget, attempts run until the deadline passes
    t = [0.0]
    monkeypatch.setattr(bench.time, "time", lambda: t[0])
    monkeypatch.setattr(bench, "_T0", 0.0)

    def advancing_child(kind, att, timeout):
        calls.append(timeout)
        t[0] += 200.0
        raise subprocess.TimeoutExpired(cmd="probe", timeout=timeout)

    monkeypatch.setattr(bench, "_run_child", advancing_child)
    with pytest.raises(RuntimeError, match="wedged"):
        bench.probe_backend(1000.0)
    assert len(calls) >= 4
