"""End-to-end reward-plane acceptance (ISSUE 14):

1. over a REAL generation server (the deterministic sim harness — real
   HTTP, pure-function token stream) and a REAL reward service, greedy
   rollout outputs are token-identical with the reward service ON vs the
   in-process pool, and chaos-injected wedged/crashing rewards leave the
   rollout plane generating: every episode completes, affected episodes
   time out per-episode (0.0 verdict), and the breaker opens and
   recovers through the /ready probe path;

2. a reward-service kill mid-batch (SIGTERM while a task wedges) leaves
   no orphaned sandbox processes — worker, task child, and a grandchild
   the task forked are all dead — and the flight-recorder dump names the
   in-flight task set.
"""

import asyncio
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request
import uuid

import numpy as np
import pytest

from areal_tpu.api.cli_args import (
    CircuitBreakerConfig,
    GenerationHyperparameters,
    InferenceEngineConfig,
    RewardServiceConfig,
)
from areal_tpu.api.io_struct import ModelRequest
from areal_tpu.api.reward_api import AsyncRewardWrapper
from areal_tpu.api.workflow_api import RolloutWorkflow
from areal_tpu.core.remote_inf_engine import RemoteInfEngine
from areal_tpu.fleet import harness
from areal_tpu.reward_service.client import RewardServiceClient
from areal_tpu.reward_service.pool import SandboxWorkerPool
from areal_tpu.utils import network
from areal_tpu.workflow.tool_loop import pack_episode

HARNESS = harness.__file__

GOOD_CODE = "answer\n```python\nprint(input().strip())\n```"
WEDGED_CODE = "hm\n```python\nimport time\ntime.sleep(300)\n```"
CRASH_CODE = "oops\n```python\nimport sys\nsys.exit(3)\n```"
CASES = [{"stdin": "7\n", "expected_stdout": "7"}]


def _wait_http(url: str, timeout: float = 30.0) -> None:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(url, timeout=2) as r:
                if r.status == 200:
                    return
        except Exception:
            time.sleep(0.1)
    raise TimeoutError(f"{url} never became ready")


@pytest.fixture()
def sim_server():
    port = network.find_free_ports(1)[0]
    proc = subprocess.Popen(
        [sys.executable, HARNESS, "--port", str(port), "--token-time",
         "0.001", "--max-concurrency", "8"],
    )
    try:
        _wait_http(f"http://127.0.0.1:{port}/ready")
        yield f"127.0.0.1:{port}"
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()


@pytest.fixture()
def reward_service_proc(tmp_path):
    port = network.find_free_ports(1)[0]
    env = dict(os.environ)
    env["AREAL_FLIGHT_RECORDER_DIR"] = str(tmp_path / "flight")
    env["AREAL_REWARD_SERVICE_ID"] = "reward-e2e"
    proc = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "areal_tpu.reward_service.service",
            "experiment_name=reward-e2e",
            "trial_name=t",
            f"name_resolve.nfs_record_root={tmp_path / 'nr'}",
            "name_resolve.type=nfs",
            f"reward_service.port={port}",
            "reward_service.num_workers=2",
            "reward_service.task_timeout=2.0",
            "reward_service.drain_grace_seconds=1.0",
        ],
        env=env,
    )
    try:
        _wait_http(f"http://127.0.0.1:{port}/ready")
        yield proc, f"127.0.0.1:{port}", tmp_path / "flight"
    finally:
        if proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()


class CodeRewardWorkflow(RolloutWorkflow):
    """Generate greedily on the sim server, then score the item's
    scripted completion through the configured reward path — the reward
    plane varies across test modes, generation must not."""

    def __init__(self, reward_fn, reward_timeout: float = 8.0):
        self.reward_fn = AsyncRewardWrapper(
            reward_fn,
            timeout=reward_timeout,
            in_process=not asyncio.iscoroutinefunction(reward_fn),
        )

    async def arun_episode(self, engine, data):
        req = ModelRequest(
            rid=str(uuid.uuid4()),
            input_ids=list(data["prompt"]),
            gconfig=GenerationHyperparameters(max_new_tokens=8, greedy=True),
        )
        resp = await engine.agenerate(req)
        reward = await self.reward_fn(
            None, data["completion"], None, None, testcases=list(CASES)
        )
        seq = list(data["prompt"]) + list(resp.output_tokens)
        loss_mask = [0] * len(data["prompt"]) + [1] * len(resp.output_tokens)
        logprobs = [0.0] * len(data["prompt"]) + list(resp.output_logprobs)
        versions = [-1] * len(data["prompt"]) + list(resp.output_versions)
        return pack_episode(seq, loss_mask, logprobs, versions, reward)


def _make_engine(addr: str, n: int, **breaker_kw) -> RemoteInfEngine:
    eng = RemoteInfEngine(
        InferenceEngineConfig(
            experiment_name="reward-e2e",
            trial_name="t",
            max_concurrent_rollouts=n,
            consumer_batch_size=n,
            request_retries=2,
            cache_aware_routing=False,
        )
    )
    eng.initialize([addr], train_data_parallel_size=1)
    return eng


def _run_batch(engine, workflow, items, timeout=120.0):
    for item in items:
        engine.submit(item, workflow=workflow)
    return engine.wait(count=len(items), timeout=timeout)


def _rows(batch) -> list[tuple]:
    """Order-independent row digests: (tokens..., reward)."""
    ids = np.asarray(batch["input_ids"])
    attn = np.asarray(batch["attention_mask"])
    rw = np.asarray(batch["rewards"]).reshape(-1)
    out = []
    for i in range(ids.shape[0]):
        n = int(attn[i].sum())
        out.append((tuple(int(t) for t in ids[i, :n]), float(rw[i])))
    return sorted(out)


def _items(n):
    return [
        {"prompt": [1, 2, 3, i], "completion": GOOD_CODE} for i in range(n)
    ]


def test_e2e_token_identity_and_wedged_rewards_dont_stall_rollout(
    sim_server, reward_service_proc
):
    _, svc_addr, _ = reward_service_proc
    n = 4

    # mode A: in-process bounded pool (zero-egress path)
    pool = SandboxWorkerPool(num_workers=2, default_timeout=2.0)
    local_cli = RewardServiceClient(
        RewardServiceConfig(task_timeout=2.0), pool=pool
    )
    eng_a = _make_engine(sim_server, n)
    try:
        wf_a = CodeRewardWorkflow(local_cli.code_reward_fn())
        batch_a = _run_batch(eng_a, wf_a, _items(n))
    finally:
        eng_a.destroy()

    # mode B: reward service ON (HTTP replica)
    svc_cli = RewardServiceClient(
        RewardServiceConfig(task_timeout=2.0, request_retries=2),
        addresses=[svc_addr],
        pool=pool,
    )
    eng_b = _make_engine(sim_server, n)
    try:
        wf_b = CodeRewardWorkflow(svc_cli.code_reward_fn())
        batch_b = _run_batch(eng_b, wf_b, _items(n))
    finally:
        eng_b.destroy()

    rows_a, rows_b = _rows(batch_a), _rows(batch_b)
    # greedy outputs token-identical service-on vs in-process, and equal
    # to the sim's analytic stream
    assert [r[0] for r in rows_a] == [r[0] for r in rows_b]
    for toks, _ in rows_a:
        prompt = list(toks[:4])
        expect = list(prompt)
        for _ in range(8):
            expect.append(harness.next_token(expect, 997))
        assert list(toks) == expect
    # rewards correct on both paths
    assert [r[1] for r in rows_a] == [1.0] * n
    assert [r[1] for r in rows_b] == [1.0] * n

    # mode C: wedged + crashing rewards — the rollout plane keeps
    # generating; affected episodes get their 0.0 verdict within the
    # per-task deadline instead of wedging anything
    eng_c = _make_engine(sim_server, n + 2)
    try:
        wf_c = CodeRewardWorkflow(svc_cli.code_reward_fn(), reward_timeout=15.0)
        items = _items(n)
        items.append({"prompt": [9, 9, 9, 1], "completion": WEDGED_CODE})
        items.append({"prompt": [9, 9, 9, 2], "completion": CRASH_CODE})
        t0 = time.monotonic()
        batch_c = _run_batch(eng_c, wf_c, items, timeout=60.0)
        wall = time.monotonic() - t0
    finally:
        eng_c.destroy()
        pool.shutdown()

    rows_c = _rows(batch_c)
    assert len(rows_c) == n + 2
    good = [r for r in rows_c if r[0][:3] != (9, 9, 9)]
    bad = [r for r in rows_c if r[0][:3] == (9, 9, 9)]
    assert [r[1] for r in good] == [1.0] * n
    assert [r[1] for r in bad] == [0.0, 0.0]
    # generation for the WEDGED episodes still produced the analytic
    # stream — the reward fault never touched the token path
    for toks, _ in bad:
        expect = list(toks[:4])
        for _ in range(8):
            expect.append(harness.next_token(expect, 997))
        assert list(toks) == expect
    # a wedged reward costs ~task_timeout, never the 300s sleep
    assert wall < 45.0

    asyncio.run(local_cli.close())
    asyncio.run(svc_cli.close())


def test_e2e_breaker_opens_and_recovers_through_probe(
    sim_server, reward_service_proc
):
    """Chaos-injected service faults mid-run: calls fail over to the
    local pool (verdicts intact), the breaker opens after the configured
    threshold, and once the fault clears the /ready probe path closes it
    and traffic returns to the service."""
    from areal_tpu.utils.chaos import ChaosPolicy

    _, svc_addr, _ = reward_service_proc
    chaos = ChaosPolicy()
    chaos.add_rule(endpoint="/run_batch", action="drop", times=2)
    pool = SandboxWorkerPool(num_workers=1, default_timeout=2.0)
    cli = RewardServiceClient(
        RewardServiceConfig(
            task_timeout=2.0,
            request_retries=1,
            request_timeout=5.0,
            breaker=CircuitBreakerConfig(
                failure_threshold=2,
                open_cooldown_seconds=0.0,
                probe_interval_seconds=0.0,
                min_window_requests=1000,
            ),
        ),
        addresses=[svc_addr],
        pool=pool,
        chaos=chaos,
    )

    async def main():
        rewards, states = [], []
        fn = cli.code_reward_fn()
        for _ in range(4):
            rewards.append(
                await fn(None, GOOD_CODE, None, None, testcases=list(CASES))
            )
            states.append(cli._health.state(svc_addr))
        await cli.close()
        return rewards, states

    try:
        rewards, states = asyncio.run(main())
    finally:
        pool.shutdown()
    # every call returned the right verdict regardless of the fault
    assert rewards == [1.0] * 4
    # step-exact: fail, trip, then recover via the probe and stay closed
    assert states == ["closed", "open", "closed", "closed"]
    assert chaos.injected == 2


def test_e2e_service_kill_mid_batch_leaves_no_orphans(
    reward_service_proc, tmp_path
):
    proc, addr, flight_dir = reward_service_proc
    pids_dir = tmp_path / "pids"
    pids_dir.mkdir()
    wedge_code = f"""
import os, time
with open({str(pids_dir)!r} + "/task", "w") as f:
    f.write(str(os.getpid()) + " " + str(os.getppid()))
pid = os.fork()
if pid == 0:
    with open({str(pids_dir)!r} + "/grandchild", "w") as f:
        f.write(str(os.getpid()))
    time.sleep(300)
    os._exit(0)
time.sleep(300)
"""

    def fire():
        req = urllib.request.Request(
            f"http://{addr}/run_batch",
            data=json.dumps(
                {
                    "uid": "killed-mid-batch",
                    "code": wedge_code,
                    "timeout": 60.0,
                    "testcases": [{"input": "", "expectedOutput": "x"}],
                }
            ).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            urllib.request.urlopen(req, timeout=30)
        except Exception:
            pass  # the kill races the response; that's the point

    t = threading.Thread(target=fire, daemon=True)
    t.start()
    # wait until the task is actually running inside a sandbox worker
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline and not (pids_dir / "grandchild").exists():
        time.sleep(0.05)
    assert (pids_dir / "grandchild").exists()
    task_pid, worker_pid = map(int, (pids_dir / "task").read_text().split())
    grandchild_pid = int((pids_dir / "grandchild").read_text())

    proc.send_signal(signal.SIGTERM)
    assert proc.wait(timeout=30) == 0
    t.join(timeout=10)

    def dead(pid):
        try:
            with open(f"/proc/{pid}/stat") as f:
                return f.read().split()[2] == "Z"
        except (FileNotFoundError, ProcessLookupError):
            return True

    for pid in (worker_pid, task_pid, grandchild_pid):
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not dead(pid):
            time.sleep(0.1)
        assert dead(pid), f"pid {pid} survived the reward-service kill"

    # the flight dump names the in-flight task set
    dumps = sorted(os.listdir(flight_dir))
    assert dumps, "SIGTERM left no flight dump"
    for name in dumps:
        snap = json.loads((flight_dir / name).read_text())
        drains = [
            e
            for e in snap.get("channels", {}).get("reward", [])
            if e["kind"] == "drain"
        ]
        if drains:
            assert any(
                uid.startswith("killed-mid-batch")
                for uid in drains[-1]["inflight_tasks"]
            )
            break
    else:
        raise AssertionError("no drain event with the in-flight task set")
