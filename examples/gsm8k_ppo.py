"""GSM8K PPO with a learned critic (reference: examples/math/gsm8k_ppo.yaml
path through the same gsm8k_grpo.py loop + PPOCritic): GAE uses the critic's
values instead of group baselines; both networks update every step.

    python -m areal_tpu.launcher.local examples/gsm8k_ppo.py --config <cfg>
"""

import json
import os
import sys

from areal_tpu.utils.device import apply_platform_env

apply_platform_env()

import numpy as np  # noqa: E402

from areal_tpu.api.alloc_mode import AllocationMode  # noqa: E402
from areal_tpu.api.cli_args import PPOConfig, load_expr_config  # noqa: E402
from areal_tpu.api.io_struct import (  # noqa: E402
    FinetuneSpec,
    StepInfo,
    WeightUpdateMeta,
)
from areal_tpu.core.remote_inf_engine import RemoteInfEngine  # noqa: E402
from areal_tpu.dataset import get_custom_dataset  # noqa: E402
from areal_tpu.engine.ppo.actor import TPUPPOActor  # noqa: E402
from areal_tpu.engine.ppo.critic import TPUPPOCritic  # noqa: E402
from areal_tpu.models.config import from_hf_config  # noqa: E402
from areal_tpu.reward import math_verify_reward  # noqa: E402
from areal_tpu.utils import logging, stats_tracker  # noqa: E402
from areal_tpu.utils.dataloader import StatefulDataLoader  # noqa: E402
from areal_tpu.utils.rl_health import RLHealthMonitor  # noqa: E402
from areal_tpu.utils.saver import Saver  # noqa: E402
from areal_tpu.utils.stats_logger import StatsLogger  # noqa: E402
from areal_tpu.workflow.rlvr import RLVRWorkflow  # noqa: E402

logger = logging.getLogger("gsm8k_ppo")


def main(argv=None):
    cfg, _ = load_expr_config(argv, PPOConfig)
    from transformers import AutoTokenizer

    tokenizer = AutoTokenizer.from_pretrained(cfg.tokenizer_path)
    rows = get_custom_dataset(
        cfg.train_dataset.path, split="train", type="rl", tokenizer=tokenizer
    )
    dataloader = StatefulDataLoader(
        rows, cfg.train_dataset.batch_size, shuffle=True, seed=cfg.seed
    )
    ft_spec = FinetuneSpec(
        total_train_epochs=cfg.total_train_epochs,
        dataset_size=len(rows),
        train_batch_size=cfg.train_dataset.batch_size,
    )
    total_steps = cfg.total_train_steps or ft_spec.total_train_steps

    alloc = AllocationMode.from_str(cfg.allocation_mode)
    rollout = RemoteInfEngine(cfg.rollout)
    rollout.initialize(None, train_data_parallel_size=alloc.train.dp if alloc.train else 1)

    actor = TPUPPOActor(cfg.actor)
    actor.create_process_group(alloc.train)
    actor.initialize(None, ft_spec)

    critic = TPUPPOCritic(cfg.critic)
    critic.create_process_group(alloc.train)
    critic.initialize(
        None, ft_spec, model_config=from_hf_config(cfg.critic.path or cfg.actor.path, is_critic=True)
    )

    weight_meta = WeightUpdateMeta.from_disk(
        cfg.experiment_name, cfg.trial_name, cfg.cluster.fileroot
    )
    actor.connect_engine(rollout, weight_meta)

    workflow = RLVRWorkflow(
        math_verify_reward, cfg.gconfig, tokenizer, in_process_reward=True
    )
    saver = Saver(cfg.saver, ft_spec)
    stats_logger = StatsLogger(cfg.stats_logger, ft_spec)

    # RL training-health observatory (same wiring as gsm8k_grpo; the PPO
    # path additionally benefits from the critic-value-driven advantages
    # flowing through the same telemetry)
    health = RLHealthMonitor.from_config(
        cfg.rl_health, pause_fn=rollout.pause
    )
    if health is not None:
        rollout.executor.rl_health = health
        actor.actor.rl_health = health

    all_rewards = []
    for global_step in range(total_steps):
        step_info = StepInfo(
            epoch=global_step // ft_spec.steps_per_epoch,
            epoch_step=global_step % ft_spec.steps_per_epoch,
            global_step=global_step,
            steps_per_epoch=ft_spec.steps_per_epoch,
        )
        with stats_tracker.record_timing("rollout"):
            if cfg.async_training:
                batch = rollout.prepare_batch(dataloader, workflow=workflow)
            else:
                batch = rollout.rollout_batch(next(iter(dataloader)), workflow=workflow)

        with stats_tracker.record_timing("compute_values"):
            batch["values"] = critic.compute_values(batch)
        if cfg.actor.recompute_logprob or cfg.actor.use_decoupled_loss:
            with stats_tracker.record_timing("recompute_logp"):
                batch["prox_logp"] = actor.actor.compute_logp(batch)
        with stats_tracker.record_timing("compute_advantage"):
            actor.actor.compute_advantages(batch)
        with stats_tracker.record_timing("train_step"):
            stats = actor.actor.ppo_update(batch)
            actor.step_lr_scheduler()
            critic_stats = critic.ppo_update(batch)
            critic.step_lr_scheduler()
        with stats_tracker.record_timing("update_weights"):
            rollout.pause()
            actor.update_weights(weight_meta)
            # an unconditional resume would silently undo the sentinel's
            # pause_rollout guardrail one step later
            if health is None or not health.rollout_paused:
                rollout.resume()

        # sentinel evaluation BEFORE the save: the halt guardrail must
        # preempt the checkpoint (a poisoned step must never become the
        # resume point)
        health_row = (
            health.end_step(global_step) if health is not None else {}
        )

        saver.save(actor, step_info, tokenizer=tokenizer)
        mean_reward = float(np.mean(np.asarray(batch["rewards"])))
        all_rewards.append(mean_reward)
        stats[0].update(stats_tracker.export(key="time_perf"))
        stats[0].update(health_row)
        stats[0]["ppo/mean_task_reward"] = mean_reward
        stats[0]["ppo/critic_loss"] = float(
            np.mean([s.get("loss", 0.0) for s in critic_stats])
        )
        stats_logger.commit(step_info.epoch, step_info.epoch_step, global_step, stats)

    out = os.path.join(stats_logger.log_dir(), "rewards.json")
    with open(out, "w") as f:
        json.dump(all_rewards, f)
    stats_logger.close()
    rollout.destroy()
    actor.destroy()
    critic.destroy()


if __name__ == "__main__":
    main(sys.argv[1:])
