"""ReAct-style search-agent workflow (reference:
examples/search-agent/tongyi_deepresearch/react_agent.py + train.py call
shape): the model interleaves reasoning with ``<search>query</search>`` and
``<visit>title</visit>`` actions; each action's observation is spliced back
as a zero-loss-mask turn (areal_tpu/workflow/tool_loop.py), up to
``max_tool_calls``; the episode's final ``<answer>...</answer>`` is scored
against the gold answer. One trajectory per episode, trained exactly like
any other RLVR rollout.

To train: build this workflow with your corpus and hand it to
``rollout.prepare_batch`` in a GRPO entry point — the full loop is
``examples/gsm8k_grpo.py``; only the workflow construction differs
(see examples/search_agent/README.md).
"""

from __future__ import annotations

import re
from typing import Any

from areal_tpu.api.cli_args import GenerationHyperparameters
from areal_tpu.api.reward_api import AsyncRewardWrapper
from areal_tpu.api.workflow_api import RolloutWorkflow
from areal_tpu.workflow.tool_loop import pack_episode, run_tool_episode

_ACTION_RE = re.compile(r"<(search|visit)>\s*(.*?)\s*</\1>", re.DOTALL)

SYSTEM_PROMPT = (
    "You are a research agent. You may use tools by emitting "
    "<search>query</search> to find documents or <visit>title</visit> to "
    "read one. Observations appear inside <observation></observation>. "
    "When confident, answer inside <answer></answer>."
)


class SearchAgentWorkflow(RolloutWorkflow):
    def __init__(
        self,
        reward_fn,
        gconfig: GenerationHyperparameters,
        tokenizer,
        env,
        max_tool_calls: int = 4,
        in_process_reward: bool = False,
        tool_metrics: bool = True,
    ):
        self.reward_fn = AsyncRewardWrapper(reward_fn, in_process=in_process_reward)
        # stop after an action tag so the tool can answer before the model
        # continues reasoning
        self.gconfig = gconfig.new(
            n_samples=1,
            stop=list(gconfig.stop) + ["</search>", "</visit>"],
        )
        self.tokenizer = tokenizer
        self.env = env
        self.max_tool_calls = max_tool_calls
        self.tool_metrics = tool_metrics

    async def arun_episode(self, engine, data: dict[str, Any]):
        messages = [{"role": "system", "content": SYSTEM_PROMPT}] + list(
            data["messages"]
        )
        prompt_ids = list(
            self.tokenizer.apply_chat_template(
                messages, tokenize=True, add_generation_prompt=True
            )
        )

        def parse(chunk: str):
            acts = _ACTION_RE.findall(chunk)
            return acts[-1] if acts else None

        async def execute(action):
            tool, arg = action
            key = "query" if tool == "search" else "title"
            obs, _ok = await self.env.aexecute(tool, {key: arg})
            return obs

        seq, loss_mask, logprobs, versions, full_text = await run_tool_episode(
            engine,
            self.tokenizer,
            self.gconfig,
            prompt_ids,
            parse,
            execute,
            lambda obs: f"\n<observation>\n{obs}\n</observation>\n",
            self.max_tool_calls,
            # actions are ("search"|"visit", arg) tuples: the default
            # action_name labels the per-tool metrics/spans by action[0]
            tool_metrics=self.tool_metrics,
        )
        reward = await self.reward_fn(
            None, full_text, None, None,
            **{k: v for k, v in data.items() if k != "messages"},
        )
        return pack_episode(seq, loss_mask, logprobs, versions, reward)


_ANSWER_RE = re.compile(r"<answer>\s*(.*?)\s*</answer>", re.DOTALL)


def search_answer_reward(
    prompt, completion, prompt_ids, completion_ids, answer: str = "", **_kw
) -> float:
    """Exact-match (normalized) on the final <answer> tag."""
    if not completion:
        return 0.0
    m = _ANSWER_RE.findall(completion)
    if not m:
        return 0.0
    got = " ".join(m[-1].split()).lower()
    want = " ".join(str(answer).split()).lower()
    return 1.0 if got == want else 0.0
