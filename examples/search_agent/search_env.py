"""Local-corpus search tools (the reference search-agent capability,
examples/search-agent/tongyi_deepresearch/tool_search.py + tool_visit.py,
re-hosted without network dependencies): ``search`` ranks corpus documents
by token overlap with the query and returns titles + snippets; ``visit``
returns a document's full text. The corpus is a list of {title, text} dicts
(or a .jsonl path) — swap in a real retrieval service by subclassing
``Environment`` the same way."""

from __future__ import annotations

import json
import re
from typing import Any

from areal_tpu.api.env_api import Environment

_TOKEN = re.compile(r"[a-z0-9]+")


def _tokens(s: str) -> set[str]:
    return set(_TOKEN.findall(s.lower()))


class LocalSearchEnv(Environment):
    def __init__(self, corpus: list[dict] | str, top_k: int = 3,
                 snippet_chars: int = 200):
        if isinstance(corpus, str):
            with open(corpus) as f:
                corpus = [json.loads(l) for l in f if l.strip()]
        self.docs = list(corpus)
        self.by_title = {d["title"]: d for d in self.docs}
        self.top_k = top_k
        self.snippet_chars = snippet_chars

    async def alist_tools(self) -> list[dict[str, Any]]:
        return [
            {
                "type": "function",
                "function": {
                    "name": "search",
                    "description": "Search the corpus; returns top titles + snippets.",
                    "parameters": {
                        "type": "object",
                        "properties": {"query": {"type": "string"}},
                        "required": ["query"],
                    },
                },
            },
            {
                "type": "function",
                "function": {
                    "name": "visit",
                    "description": "Fetch a document's full text by its title.",
                    "parameters": {
                        "type": "object",
                        "properties": {"title": {"type": "string"}},
                        "required": ["title"],
                    },
                },
            },
        ]

    async def aexecute(
        self, tool_name: str, arguments: dict[str, Any], timeout: float | None = None
    ) -> tuple[str, bool]:
        if tool_name == "search":
            q = _tokens(arguments.get("query", ""))
            if not q:
                return "empty query", False
            hits = [
                (len(q & _tokens(d["title"] + " " + d["text"])), d)
                for d in self.docs
            ]
            hits = sorted(
                (h for h in hits if h[0] > 0), key=lambda h: -h[0]
            )[: self.top_k]
            lines = [
                f"[{d['title']}] {d['text'][: self.snippet_chars]}"
                for _, d in hits
            ]
            return "\n".join(lines) if lines else "no results", True
        if tool_name == "visit":
            d = self.by_title.get(arguments.get("title", ""))
            if d is None:
                return f"no document titled {arguments.get('title')!r}", False
            return d["text"], True
        return f"unknown tool {tool_name}", False
