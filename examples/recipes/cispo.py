"""Recipe: CISPO-style clipped-importance-sampling policy loss.

This mirrors the reference's recipe extension pattern (recipe/AEnt/actor.py:
subclass the actor, swap the loss fn, keep everything else — rollout,
advantages, microbatching, optimizer — untouched). AEnt's clamped-entropy
bonus is already a built-in knob here (cli_args entropy_coeff/entropy_clamp),
so this recipe demonstrates the pattern with a different variant:

    L = - E[ stop_grad(min(ratio, 1 + eps_max)) * logp * advantage ]

i.e. a REINFORCE-style surrogate whose importance weight is clipped and
detached (the CISPO formulation) instead of PPO's clipped-ratio objective.

Run it exactly like GRPO — same launcher, same config — with this module's
actor:

    python -m areal_tpu.launcher.local examples/recipes/cispo.py \
        --config examples/configs/gsm8k_grpo.yaml
"""

from __future__ import annotations

import functools
import sys
from typing import Any

import jax
import jax.numpy as jnp

from areal_tpu.api.cli_args import PPOActorConfig
from areal_tpu.engine.ppo.actor import PPOActor, TPUPPOActor
from areal_tpu.utils.functional import gather_logprobs_entropy


def cispo_loss_fn(
    logits: jnp.ndarray,
    input_data: dict[str, Any],
    temperature: float,
    eps_max: float,
    entropy_coeff: float = 0.0,
    entropy_clamp: float | None = None,
):
    """SUM-reduced (the engine divides by the global valid-token count)."""
    labels = jnp.roll(input_data["input_ids"], shift=-1)
    logprobs, entropy = gather_logprobs_entropy(logits, labels, temperature)
    behav = input_data["logprobs"]  # behavior-policy logprobs from rollout
    adv = input_data["advantages"]
    mask = input_data["loss_mask"].astype(bool)

    ratio = jnp.exp(logprobs - behav)
    w = jax.lax.stop_gradient(jnp.minimum(ratio, 1.0 + eps_max))
    loss_tok = -w * logprobs * adv
    if "loss_agg_w" in input_data:
        # honor seq-mean aggregation modes (log_agg_mode) like grpo_loss_fn
        loss_tok = loss_tok * input_data["loss_agg_w"]
    loss = jnp.sum(jnp.where(mask, loss_tok, 0.0))
    if entropy_coeff != 0.0:
        # honor the built-in AEnt knobs here too: a replaced loss must not
        # silently kill config switches
        ent = entropy
        if entropy_clamp is not None:
            ent = jnp.minimum(ent, entropy_clamp)
        loss = loss - entropy_coeff * jnp.sum(jnp.where(mask, ent, 0.0))
    return loss


class CISPOActor(PPOActor):
    """PPOActor with the loss swapped — nothing else changes.

    ``eps_max`` defaults to the config's clip-higher knob
    (``actor.eps_clip_higher``) so the threshold stays tunable through the
    normal YAML/CLI path when running via ``main()``.
    """

    def __init__(
        self, config: PPOActorConfig, engine, eps_max: float | None = None
    ):
        super().__init__(config, engine)
        if eps_max is None:
            eps_max = config.eps_clip_higher or 0.28
        self._loss_fn = functools.partial(
            cispo_loss_fn,
            temperature=self.temperature,
            eps_max=eps_max,
            entropy_coeff=config.entropy_coeff,
            entropy_clamp=config.entropy_clamp,
        )


class TPUCISPOActor(TPUPPOActor):
    actor_cls = CISPOActor


def main(argv=None):
    # the GRPO entry point drives everything; only the actor class differs
    import examples.gsm8k_grpo as grpo

    orig = grpo.TPUPPOActor
    grpo.TPUPPOActor = TPUCISPOActor
    try:
        grpo.main(argv)
    finally:
        grpo.TPUPPOActor = orig


if __name__ == "__main__":
    main(sys.argv[1:])
