"""GSM8K SFT entry point — the minimum end-to-end workload (reference:
examples/math/gsm8k_sft.py + SURVEY §3.5): packed cross-entropy on the GSPMD
mesh, saver/evaluator/recover wiring, no inference engine.

    python examples/gsm8k_sft.py --config examples/configs/gsm8k_sft.yaml
"""

import sys

from areal_tpu.utils.device import apply_platform_env

apply_platform_env()

from areal_tpu.parallel import distributed  # noqa: E402

# no-op single-process; connects the jax.distributed mesh when the launcher
# set AREAL_COORDINATOR_ADDR/AREAL_NUM_PROCESSES/AREAL_PROCESS_ID
distributed.initialize()

import numpy as np  # noqa: E402

from areal_tpu.api.alloc_mode import AllocationMode  # noqa: E402
from areal_tpu.api.cli_args import SFTConfig, load_expr_config  # noqa: E402
from areal_tpu.api.io_struct import FinetuneSpec, StepInfo  # noqa: E402
from areal_tpu.dataset import get_custom_dataset  # noqa: E402
from areal_tpu.engine.sft.lm_engine import TPULMEngine  # noqa: E402
from areal_tpu.utils import logging, stats_tracker  # noqa: E402
from areal_tpu.utils.data import pad_sequences_to_tensors  # noqa: E402
from areal_tpu.utils.dataloader import StatefulDataLoader  # noqa: E402
from areal_tpu.utils.profiling import StepProfiler  # noqa: E402
from areal_tpu.utils.recover import RecoverHandler, check_if_recover  # noqa: E402
from areal_tpu.utils.saver import Evaluator, Saver  # noqa: E402
from areal_tpu.utils.stats_logger import StatsLogger  # noqa: E402
from areal_tpu.utils.step_timeline import StepTimeline  # noqa: E402

logger = logging.getLogger("gsm8k_sft")


def main(argv=None):
    cfg, _ = load_expr_config(argv, SFTConfig)

    from transformers import AutoTokenizer

    tokenizer = AutoTokenizer.from_pretrained(cfg.tokenizer_path)

    rows = get_custom_dataset(
        cfg.train_dataset.path,
        split="train",
        type="sft",
        tokenizer=tokenizer,
        max_length=cfg.train_dataset.max_length,
    )
    rows = distributed.shard_rows(rows)  # per-host DP shard (multi-host)
    dataloader = StatefulDataLoader(
        rows,
        cfg.train_dataset.batch_size,
        shuffle=cfg.train_dataset.shuffle,
        seed=cfg.seed,
        drop_last=cfg.train_dataset.drop_last,
        collate_fn=pad_sequences_to_tensors,
    )
    valid_loader = None
    if cfg.valid_dataset is not None and cfg.valid_dataset.path:
        valid_rows = get_custom_dataset(
            cfg.valid_dataset.path,
            split="test",
            type="sft",
            tokenizer=tokenizer,
            max_length=cfg.valid_dataset.max_length,
        )
        valid_loader = StatefulDataLoader(
            valid_rows,
            cfg.valid_dataset.batch_size,
            shuffle=False,
            drop_last=False,
            collate_fn=pad_sequences_to_tensors,
        )

    ft_spec = FinetuneSpec(
        total_train_epochs=cfg.total_train_epochs,
        dataset_size=len(rows),
        train_batch_size=cfg.train_dataset.batch_size,
    )
    total_steps = cfg.total_train_steps or ft_spec.total_train_steps

    alloc = AllocationMode.from_str(cfg.allocation_mode)
    engine = TPULMEngine(cfg.model)
    engine.create_process_group(alloc.train)
    engine.initialize(None, ft_spec)

    saver = Saver(cfg.saver, ft_spec)
    evaluator = Evaluator(cfg.evaluator, ft_spec)
    recover_handler = RecoverHandler(cfg.recover, ft_spec)
    slogger = StatsLogger(cfg.stats_logger, ft_spec)

    start_step = 0
    if check_if_recover(cfg.recover):
        info = recover_handler.load(
            engine,
            saver,
            evaluator,
            dataloader,
            fileroot=cfg.cluster.fileroot,
            experiment_name=cfg.experiment_name,
            trial_name=cfg.trial_name,
            config=cfg,
        )
        if info is not None:
            start_step = info.last_step_info.global_step + 1

    data_iter = iter(dataloader)
    losses = []
    profiler = StepProfiler(cfg.profiler)
    # training-plane goodput observatory (no rollout plane here: the SFT
    # breakdown is data / train_step / checkpoint — the minimal shape)
    timeline = StepTimeline.from_config(
        cfg.step_timeline,
        model_config=engine.model_config,
        n_chips=engine.mesh.size if engine.mesh is not None else 1,
    )
    try:
        for global_step in range(start_step, total_steps):
            step_info = StepInfo(
                epoch=global_step // ft_spec.steps_per_epoch,
                epoch_step=global_step % ft_spec.steps_per_epoch,
                global_step=global_step,
                steps_per_epoch=ft_spec.steps_per_epoch,
            )
            timeline.begin_step(global_step)
            with timeline.phase("data"):
                try:
                    batch = next(data_iter)
                except StopIteration:
                    data_iter = iter(dataloader)
                    batch = next(data_iter)

            with profiler.step(global_step), timeline.phase(
                "train_step"
            ), stats_tracker.record_timing("train_step"):
                stats = engine.train_lm(batch)
                engine.step_lr_scheduler()
            losses.append(stats["loss"])

            def eval_fn():
                if valid_loader is None:
                    return
                vl = [engine.evaluate_lm(vb) for vb in valid_loader]
                vl = [x for x in vl if x is not None]
                if vl:
                    stats_tracker.scalar(eval_loss=float(np.mean(vl)))

            with timeline.phase("checkpoint"):
                saver.save(engine, step_info, tokenizer=tokenizer)
                evaluator.evaluate(eval_fn, step_info)
                recover_handler.dump(
                    engine,
                    step_info,
                    saver,
                    evaluator,
                    dataloader,
                    slogger,
                    fileroot=cfg.cluster.fileroot,
                    experiment_name=cfg.experiment_name,
                    trial_name=cfg.trial_name,
                    tokenizer=tokenizer,
                    config=cfg,
                )
            attn = np.asarray(batch["attention_mask"])
            stats.update(
                timeline.end_step(
                    tokens=int(attn.sum()), n_seqs=int(attn.shape[0])
                )
            )
            stats.update(stats_tracker.export())
            slogger.commit(step_info.epoch, step_info.epoch_step, global_step, stats)

    finally:
        # a capture window that spans the exit (short run, crash,
        # StopIteration mid-window) must still flush its trace
        profiler.close()
        timeline.close()
    logger.info("final loss %.4f (start %.4f)", losses[-1], losses[0])
    slogger.close()
    engine.destroy()
    return losses


if __name__ == "__main__":
    main(sys.argv[1:])
