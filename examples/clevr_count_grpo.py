"""CLEVR-count GRPO — the VLM RL entry point, mirroring the reference's
examples/vlm/clevr_count_70k_grpo.py call stack: VisionRLVRWorkflow rollouts
(images ride the generation request), count reward on the gold object count,
decoupled-PPO updates through the vision encoder on the GSPMD train mesh.

Run under the local launcher (which starts the generation servers first):

    python -m areal_tpu.launcher.local examples/clevr_count_grpo.py \
        --config examples/configs/clevr_count_grpo.yaml
"""

import json
import os
import sys

from areal_tpu.utils.device import apply_platform_env

apply_platform_env()

from areal_tpu.parallel import distributed  # noqa: E402

distributed.initialize()

import numpy as np  # noqa: E402

from areal_tpu.api.alloc_mode import AllocationMode  # noqa: E402
from areal_tpu.api.cli_args import GRPOConfig, load_expr_config  # noqa: E402
from areal_tpu.api.io_struct import (  # noqa: E402
    FinetuneSpec,
    StepInfo,
    WeightUpdateMeta,
)
from areal_tpu.core.remote_inf_engine import RemoteInfEngine  # noqa: E402
from areal_tpu.dataset import get_custom_dataset  # noqa: E402
from areal_tpu.engine.ppo.actor import TPUPPOActor  # noqa: E402
from areal_tpu.models.config import from_hf_config  # noqa: E402
from areal_tpu.reward.count_reward import count_reward  # noqa: E402
from areal_tpu.utils import logging, stats_tracker  # noqa: E402
from areal_tpu.utils.dataloader import StatefulDataLoader  # noqa: E402
from areal_tpu.utils.recover import RecoverHandler, check_if_recover  # noqa: E402
from areal_tpu.utils.saver import Evaluator, Saver  # noqa: E402
from areal_tpu.utils.stats_logger import StatsLogger  # noqa: E402
from areal_tpu.workflow.vision_rlvr import VisionRLVRWorkflow  # noqa: E402

logger = logging.getLogger("clevr_count_grpo")


def main(argv=None):
    cfg, _ = load_expr_config(argv, GRPOConfig)

    from transformers import AutoTokenizer

    tokenizer = AutoTokenizer.from_pretrained(cfg.tokenizer_path)

    # the vision splice geometry comes from the model config: each image
    # becomes exactly `vision_patches` embedding rows at `image_token_id`
    model_cfg = from_hf_config(cfg.actor.path)
    if not model_cfg.is_vlm:
        raise ValueError(
            f"{cfg.actor.path} has no vision tower; clevr_count requires a "
            "VLM checkpoint (vision_patch_size > 0)"
        )

    train_rows = get_custom_dataset(
        cfg.train_dataset.path,
        split="train",
        type=cfg.train_dataset.type,
        tokenizer=tokenizer,
        max_length=cfg.train_dataset.max_length,
    )
    dataloader = StatefulDataLoader(
        train_rows,
        cfg.train_dataset.batch_size,
        shuffle=cfg.train_dataset.shuffle,
        seed=cfg.seed,
        drop_last=cfg.train_dataset.drop_last,
    )
    ft_spec = FinetuneSpec(
        total_train_epochs=cfg.total_train_epochs,
        dataset_size=len(train_rows),
        train_batch_size=cfg.train_dataset.batch_size,
    )
    total_steps = cfg.total_train_steps or ft_spec.total_train_steps

    rollout = RemoteInfEngine(cfg.rollout)
    alloc = AllocationMode.from_str(cfg.allocation_mode)
    rollout.initialize(
        None, train_data_parallel_size=alloc.train.dp if alloc.train else 1
    )

    actor = TPUPPOActor(cfg.actor)
    actor.create_process_group(alloc.train)
    actor.initialize(None, ft_spec)

    if cfg.weight_update == "http":
        weight_meta = WeightUpdateMeta.from_http()
    elif cfg.weight_update == "disk":
        weight_meta = WeightUpdateMeta.from_disk(
            cfg.experiment_name, cfg.trial_name, cfg.cluster.fileroot
        )
    else:
        raise ValueError(
            f"weight_update must be 'disk' or 'http', got {cfg.weight_update!r}"
        )
    actor.connect_engine(rollout, weight_meta)

    log_dir = os.path.join(
        cfg.stats_logger.fileroot, cfg.experiment_name, cfg.trial_name, "logs"
    )
    workflow = VisionRLVRWorkflow(
        count_reward,
        cfg.gconfig,
        tokenizer,
        image_token_id=model_cfg.image_token_id,
        patches_per_image=model_cfg.vision_patches,
        dump_dir=os.path.join(log_dir, "generated"),
        in_process_reward=True,
    )

    saver = Saver(cfg.saver, ft_spec)
    evaluator = Evaluator(cfg.evaluator, ft_spec)
    recover_handler = RecoverHandler(cfg.recover, ft_spec)
    stats_logger = StatsLogger(cfg.stats_logger, ft_spec)

    start_step = 0
    if check_if_recover(cfg.recover):
        info = recover_handler.load(
            actor,
            saver,
            evaluator,
            dataloader,
            fileroot=cfg.cluster.fileroot,
            experiment_name=cfg.experiment_name,
            trial_name=cfg.trial_name,
            config=cfg,
        )
        if info is not None:
            start_step = info.last_step_info.global_step + 1
            actor.update_weights(weight_meta)

    all_rewards = []
    for global_step in range(start_step, total_steps):
        step_info = StepInfo(
            epoch=global_step // ft_spec.steps_per_epoch,
            epoch_step=global_step % ft_spec.steps_per_epoch,
            global_step=global_step,
            steps_per_epoch=ft_spec.steps_per_epoch,
        )

        with stats_tracker.record_timing("rollout"):
            if cfg.async_training:
                batch = rollout.prepare_batch(dataloader, workflow=workflow)
            else:
                batch = rollout.rollout_batch(
                    next(iter(dataloader)), workflow=workflow
                )

        if cfg.actor.recompute_logprob or cfg.actor.use_decoupled_loss:
            with stats_tracker.record_timing("recompute_logp"):
                batch["prox_logp"] = actor.actor.compute_logp(batch)

        with stats_tracker.record_timing("compute_advantage"):
            actor.actor.compute_advantages(batch)

        with stats_tracker.record_timing("train_step"):
            stats = actor.actor.ppo_update(batch)
            actor.step_lr_scheduler()

        with stats_tracker.record_timing("update_weights"):
            rollout.pause()
            actor.update_weights(weight_meta)
            rollout.resume()

        with stats_tracker.record_timing("save"):
            saver.save(actor, step_info, tokenizer=tokenizer)
            recover_handler.dump(
                actor,
                step_info,
                saver,
                evaluator,
                dataloader,
                stats_logger,
                fileroot=cfg.cluster.fileroot,
                experiment_name=cfg.experiment_name,
                trial_name=cfg.trial_name,
                tokenizer=tokenizer,
                config=cfg,
            )

        mean_reward = float(np.mean(np.asarray(batch["rewards"])))
        all_rewards.append(mean_reward)
        stats[0].update(stats_tracker.export(key="time_perf"))
        stats[0]["grpo/mean_task_reward"] = mean_reward
        stats_logger.commit(step_info.epoch, step_info.epoch_step, global_step, stats)

    out = os.path.join(stats_logger.log_dir(), "rewards.json")
    with open(out, "w") as f:
        json.dump(all_rewards, f)
    logger.info("wrote %s", out)

    stats_logger.close()
    rollout.destroy()
    actor.destroy()


if __name__ == "__main__":
    main(sys.argv[1:])
