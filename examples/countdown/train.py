"""Countdown GRPO (reference: examples/countdown/train.py): custom dataset
rows {target, nums} + the equation-verifier reward, same training loop as
gsm8k_grpo.

    python -m areal_tpu.launcher.local examples/countdown/train.py --config <cfg>
"""

import sys

from areal_tpu.utils.device import apply_platform_env

apply_platform_env()


def main(argv=None):
    import examples.gsm8k_grpo as base
    from examples.countdown.reward_score import countdown_reward

    base.math_verify_reward = countdown_reward
    base.main(argv)


if __name__ == "__main__":
    main(sys.argv[1:])
