"""Countdown-task reward (reference: examples/countdown/reward_score.py
capability): the model must combine the given numbers with + - * / to reach
the target; the reward checks the proposed equation actually evaluates to the
target and uses each number exactly once."""

from __future__ import annotations

import ast
import re


def _extract_equation(text: str) -> str | None:
    m = re.findall(r"<answer>(.*?)</answer>", text, re.DOTALL)
    if m:
        return m[-1].strip()
    m = re.findall(r"([\d\s\+\-\*/\(\)\.]+)=", text)
    return m[-1].strip() if m else None


def _numbers_used(expr: str) -> list[int]:
    return [int(x) for x in re.findall(r"\d+", expr)]


def _safe_eval(expr: str) -> float | None:
    """Arithmetic-only evaluation (no names/calls)."""
    try:
        node = ast.parse(expr, mode="eval")
    except SyntaxError:
        return None
    allowed = (
        ast.Expression, ast.BinOp, ast.UnaryOp, ast.Constant,
        ast.Add, ast.Sub, ast.Mult, ast.Div, ast.USub, ast.UAdd,
    )
    for sub in ast.walk(node):
        if not isinstance(sub, allowed):
            return None
    try:
        return float(eval(compile(node, "<eq>", "eval"), {"__builtins__": {}}))
    except (ZeroDivisionError, OverflowError, ValueError):
        return None


def countdown_reward(
    prompt, completion, prompt_ids, completion_ids,
    target: int | None = None, nums: list[int] | None = None, **kwargs,
) -> float:
    if completion is None or target is None or nums is None:
        return 0.0
    eq = _extract_equation(completion)
    if eq is None:
        return 0.0
    if sorted(_numbers_used(eq)) != sorted(int(n) for n in nums):
        return 0.0
    val = _safe_eval(eq)
    if val is None:
        return 0.0
    return 1.0 if abs(val - float(target)) < 1e-6 else 0.0
