"""Multi-turn self-correction GRPO (reference: examples/multi-turn-math/train.py):
identical to gsm8k_grpo except the rollout workflow retries wrong answers
with a canned prompt and discounts later-turn rewards.

    python -m areal_tpu.launcher.local examples/multi_turn_math.py --config <cfg>
"""

import sys

from areal_tpu.utils.device import apply_platform_env

apply_platform_env()


def main(argv=None):
    import examples.gsm8k_grpo as base
    from areal_tpu.workflow.multi_turn import MultiTurnWorkflow

    # swap the workflow the base entry constructs; every other step of the
    # loop (logp, advantages, updates, weight push) is unchanged
    def build_workflow(reward_fn, gconfig, tokenizer, **kw):
        return MultiTurnWorkflow(
            reward_fn,
            gconfig,
            tokenizer,
            max_turns=3,
            turn_discount=0.9,
            in_process_reward=kw.get("in_process_reward", True),
        )

    base.RLVRWorkflow = build_workflow
    base.main(argv)


if __name__ == "__main__":
    main(sys.argv[1:])
