"""Reward-model training on preference pairs (reference:
examples/alignment/hhrlhf_rw.py): pairwise Bradley-Terry loss on a
critic-headed decoder via TPURWEngine.

    python examples/hhrlhf_rw.py --config examples/configs/hhrlhf_rw.yaml
"""

import sys

from areal_tpu.utils.device import apply_platform_env

apply_platform_env()

import numpy as np  # noqa: E402

from areal_tpu.api.alloc_mode import AllocationMode  # noqa: E402
from areal_tpu.api.cli_args import RWConfig, load_expr_config  # noqa: E402
from areal_tpu.api.io_struct import FinetuneSpec, StepInfo  # noqa: E402
from areal_tpu.dataset import get_custom_dataset  # noqa: E402
from areal_tpu.engine.rw import TPURWEngine  # noqa: E402
from areal_tpu.models.config import from_hf_config  # noqa: E402
from areal_tpu.utils import logging  # noqa: E402
from areal_tpu.utils.data import pad_sequences_to_tensors  # noqa: E402
from areal_tpu.utils.dataloader import StatefulDataLoader  # noqa: E402
from areal_tpu.utils.saver import Saver  # noqa: E402
from areal_tpu.utils.stats_logger import StatsLogger  # noqa: E402

logger = logging.getLogger("hhrlhf_rw")


class _PairLoader(StatefulDataLoader):
    """Batches must hold whole pairs: rows are (chosen, rejected) alternating,
    so shuffle at PAIR granularity."""

    def _order(self, epoch):
        import random

        n_pairs = len(self.dataset) // 2
        pairs = list(range(n_pairs))
        if self.shuffle:
            random.Random((self.seed, epoch).__hash__()).shuffle(pairs)
        return [2 * p + j for p in pairs for j in (0, 1)]


def main(argv=None):
    cfg, _ = load_expr_config(argv, RWConfig)
    from transformers import AutoTokenizer

    tokenizer = AutoTokenizer.from_pretrained(cfg.tokenizer_path)
    rows = get_custom_dataset(
        cfg.train_dataset.path,
        split="train",
        type="rw",
        tokenizer=tokenizer,
        max_length=cfg.train_dataset.max_length,
    )
    # batch_size counts PAIRS; loader rows are 2x
    loader = _PairLoader(
        rows,
        cfg.train_dataset.batch_size * 2,
        shuffle=cfg.train_dataset.shuffle,
        seed=cfg.seed,
        collate_fn=pad_sequences_to_tensors,
    )
    ft_spec = FinetuneSpec(
        total_train_epochs=cfg.total_train_epochs,
        dataset_size=len(rows) // 2,
        train_batch_size=cfg.train_dataset.batch_size,
    )
    total_steps = cfg.total_train_steps or ft_spec.total_train_steps

    alloc = AllocationMode.from_str(cfg.allocation_mode)
    engine = TPURWEngine(cfg.model)
    engine.create_process_group(alloc.train)
    engine.initialize(
        None, ft_spec, model_config=from_hf_config(cfg.model.path, is_critic=True)
    )

    saver = Saver(cfg.saver, ft_spec)
    slogger = StatsLogger(cfg.stats_logger, ft_spec)
    it = iter(loader)
    for global_step in range(total_steps):
        step_info = StepInfo(
            epoch=global_step // ft_spec.steps_per_epoch,
            epoch_step=global_step % ft_spec.steps_per_epoch,
            global_step=global_step,
            steps_per_epoch=ft_spec.steps_per_epoch,
        )
        try:
            batch = next(it)
        except StopIteration:
            it = iter(loader)
            batch = next(it)
        stats = engine.train_rm(batch)
        saver.save(engine, step_info, tokenizer=tokenizer)
        slogger.commit(step_info.epoch, step_info.epoch_step, global_step, stats)
    slogger.close()
    engine.destroy()


if __name__ == "__main__":
    main(sys.argv[1:])
