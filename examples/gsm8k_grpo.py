"""GSM8K GRPO training entry point — the framework's flagship single-file
experiment, mirroring the reference's examples/math/gsm8k_grpo.py:34-263 call
stack: rollout via the remote generation servers, decoupled-PPO updates on the
GSPMD train mesh, disk weight push each step, saver/evaluator/recover wiring.

Run under the local launcher (which starts the generation servers first):

    python -m areal_tpu.launcher.local examples/gsm8k_grpo.py \
        --config examples/configs/gsm8k_grpo.yaml
"""

import json
import os
import sys

from areal_tpu.utils.device import apply_platform_env

apply_platform_env()

from areal_tpu.parallel import distributed  # noqa: E402

# no-op single-process; under multi-host (jax.distributed), host 0 becomes
# the rollout head and the other hosts receive their row shards through
# RemoteInfEngine's per-step broadcast+shard scatter
distributed.initialize()

import numpy as np  # noqa: E402

from areal_tpu.api.alloc_mode import AllocationMode  # noqa: E402
from areal_tpu.api.cli_args import GRPOConfig, load_expr_config  # noqa: E402
from areal_tpu.api.io_struct import (  # noqa: E402
    FinetuneSpec,
    StepInfo,
    WeightUpdateMeta,
)
from areal_tpu.core.remote_inf_engine import RemoteInfEngine  # noqa: E402
from areal_tpu.core.workflow_executor import RolloutWaitInterrupted  # noqa: E402
from areal_tpu.dataset import get_custom_dataset  # noqa: E402
from areal_tpu.engine.ppo.actor import PPOActor, TPUPPOActor  # noqa: E402
from areal_tpu.engine.train_engine import TPUTrainEngine  # noqa: E402
from areal_tpu.reward import math_verify_reward  # noqa: E402
from areal_tpu.utils import logging, stats_tracker  # noqa: E402
from areal_tpu.utils.chaos import crash_point  # noqa: E402
from areal_tpu.utils.dataloader import StatefulDataLoader  # noqa: E402
from areal_tpu.utils.profiling import StepProfiler  # noqa: E402
from areal_tpu.utils.rl_health import RLHealthMonitor  # noqa: E402
from areal_tpu.utils.recover import (  # noqa: E402
    PREEMPTION_EXIT_CODE,
    PreemptionGuard,
    RecoverHandler,
    check_if_recover,
)
from areal_tpu.utils.saver import Evaluator, Saver  # noqa: E402
from areal_tpu.utils.stats_logger import StatsLogger  # noqa: E402
from areal_tpu.utils.step_timeline import StepTimeline  # noqa: E402
from areal_tpu.utils.watchdog import Watchdog  # noqa: E402
from areal_tpu.workflow.rlvr import RLVRWorkflow  # noqa: E402

logger = logging.getLogger("gsm8k_grpo")


def main(argv=None):
    cfg, _ = load_expr_config(argv, GRPOConfig)

    from transformers import AutoTokenizer

    tokenizer = AutoTokenizer.from_pretrained(cfg.tokenizer_path)

    train_rows = get_custom_dataset(
        cfg.train_dataset.path,
        split="train",
        type=cfg.train_dataset.type,
        tokenizer=tokenizer,
        max_length=cfg.train_dataset.max_length,
    )
    dataloader = StatefulDataLoader(
        train_rows,
        cfg.train_dataset.batch_size,
        shuffle=cfg.train_dataset.shuffle,
        seed=cfg.seed,
        drop_last=cfg.train_dataset.drop_last,
    )
    ft_spec = FinetuneSpec(
        total_train_epochs=cfg.total_train_epochs,
        dataset_size=len(train_rows),
        train_batch_size=cfg.train_dataset.batch_size,
    )
    # budget precedence: explicit steps > sequence budget > epoch-derived
    if cfg.total_train_steps:
        total_steps = cfg.total_train_steps
    elif cfg.total_train_n_seqs:
        total_steps = max(
            1, cfg.total_train_n_seqs // cfg.train_dataset.batch_size
        )
    else:
        total_steps = ft_spec.total_train_steps

    # rollout client (generation servers were started by the launcher)
    rollout = RemoteInfEngine(cfg.rollout)
    alloc = AllocationMode.from_str(cfg.allocation_mode)
    rollout.initialize(None, train_data_parallel_size=alloc.train.dp if alloc.train else 1)

    # elastic fleet (optional): close the load -> fleet-size loop on a
    # background thread; the provider spawns servers with the launcher's
    # exported argv template (AREAL_FLEET_SERVER_ARGV)
    fleet_controller = None
    if cfg.rollout.fleet.enabled:
        from areal_tpu.fleet import build_controller

        fleet_controller = build_controller(rollout)
        fleet_controller.start()

    # actor on the train mesh
    actor = TPUPPOActor(cfg.actor)
    actor.create_process_group(alloc.train)
    actor.initialize(None, ft_spec)

    if cfg.weight_update == "http":
        weight_meta = WeightUpdateMeta.from_http()
    elif cfg.weight_update == "disk":
        weight_meta = WeightUpdateMeta.from_disk(
            cfg.experiment_name, cfg.trial_name, cfg.cluster.fileroot
        )
    else:
        raise ValueError(
            f"weight_update must be 'disk' or 'http', got {cfg.weight_update!r}"
        )
    actor.connect_engine(rollout, weight_meta)

    ref: PPOActor | None = None
    if cfg.ref is not None and cfg.actor.kl_ctl != 0.0:
        ref_engine = TPUTrainEngine(cfg.ref)
        ref_engine.create_process_group(alloc.train)
        ref_engine.initialize(None, ft_spec)
        # Wrap so the frozen reference policy can compute logprobs; the KL
        # penalty must compare actor vs ref weights, not actor vs itself.
        ref = PPOActor(cfg.actor, ref_engine)

    # sandboxed reward-execution plane: installs the service client
    # (discovery + breakers + local-pool fallback) when enabled; the tool
    # env and any code-verification reward route through it. A no-op for
    # the default math reward below, which is trivially fast in-process.
    if getattr(cfg, "reward_service", None) is not None:
        import areal_tpu.reward_service as reward_service_plane

        reward_service_plane.configure(
            cfg.reward_service, cfg.experiment_name, cfg.trial_name
        )

    log_dir = os.path.join(
        cfg.stats_logger.fileroot, cfg.experiment_name, cfg.trial_name, "logs"
    )
    workflow = RLVRWorkflow(
        math_verify_reward,
        cfg.gconfig,
        tokenizer,
        dump_dir=os.path.join(log_dir, "generated"),
        in_process_reward=True,
    )

    saver = Saver(cfg.saver, ft_spec)
    evaluator = Evaluator(cfg.evaluator, ft_spec)
    recover_handler = RecoverHandler(cfg.recover, ft_spec)
    stats_logger = StatsLogger(cfg.stats_logger, ft_spec)

    # preemption plane: SIGTERM arms the guard; the loop below notices at
    # the next step boundary and drains + checkpoints within the grace
    # budget. The watchdog is the inverse guard: a trainer that STOPS
    # beating (wedged collective, hung rollout wait) dumps stacks and exits
    # nonzero so the launcher restarts it from the last recover dump.
    guard = PreemptionGuard(cfg.recover.grace_period_seconds).install()
    watchdog = Watchdog(cfg.watchdog).start()
    # a SIGTERM mid-rollout-wait must interrupt the wait (it dominates
    # wall-clock) instead of burning the grace budget until the next step
    rollout.executor.interrupt_check = guard.should_stop

    recover_kwargs = dict(
        fileroot=cfg.cluster.fileroot,
        experiment_name=cfg.experiment_name,
        trial_name=cfg.trial_name,
    )

    start_step = 0
    if check_if_recover(cfg.recover):
        info = recover_handler.load(
            actor,
            saver,
            evaluator,
            dataloader,
            stats_logger,
            config=cfg,
            rollout=rollout,
            **recover_kwargs,
        )
        if info is not None:
            start_step = info.last_step_info.global_step + 1
            # re-sync the inference plane BEFORE the first resumed rollout:
            # servers may be fresh restarts (version 0) or hold updates the
            # recovered trainer rolled back past. Write the recovered
            # weights to the fan-out path and re-push to every server whose
            # version mismatches (reusing the version-checked rejoin probe's
            # machinery); no resumed rollout is accepted before this.
            actor.set_version(info.weight_version)
            if cfg.weight_update == "disk":
                actor.upload_weights(weight_meta)
                rollout.reconcile_after_recover(
                    weight_meta, info.weight_version
                )
            else:
                actor.update_weights(weight_meta)  # full re-push

    profiler = StepProfiler(cfg.profiler)
    # training-plane goodput observatory: per-step phase attribution,
    # goodput/MFU, memory+recompile telemetry, a `trainer` flight-recorder
    # channel, and one train.step tracing span per step (sharing the
    # rollout client's tracer so trainer + rollout spans land in ONE
    # Perfetto export, joined by weight version)
    timeline = StepTimeline.from_config(
        cfg.step_timeline,
        tracer=rollout._tracer,
        model_config=actor.model_config,
        n_chips=actor.mesh.size if actor.mesh is not None else 1,
    )
    # RL training-health observatory: per-step staleness/ratio/reward/
    # entropy distribution telemetry + the anomaly sentinel. The monitor
    # reads the update path's own arrays (actor hooks) and collected
    # rollout batches (executor hook); a firing rule records an `anomaly`
    # flight entry + dump and drives the configured guardrail —
    # pause_rollout stops feeding episodes, halt raises BEFORE this step's
    # checkpoint commits so a poisoned step never becomes the resume point.
    health = RLHealthMonitor.from_config(
        cfg.rl_health, pause_fn=rollout.pause
    )
    if health is not None:
        rollout.executor.rl_health = health
        actor.actor.rl_health = health
    all_rewards = []
    try:
        for global_step in range(start_step, total_steps):
            step_info = StepInfo(
                epoch=global_step // ft_spec.steps_per_epoch,
                epoch_step=global_step % ft_spec.steps_per_epoch,
                global_step=global_step,
                steps_per_epoch=ft_spec.steps_per_epoch,
            )

            def graceful_exit():
                # SIGTERM/preemption notice: pause -> drain in-flight
                # rollouts -> forced dump at the last COMPLETED step, then
                # exit nonzero so the launcher relaunches into a resume.
                # With no step completed in THIS process there is nothing
                # new to dump — the previous dump (if any) is still valid.
                if global_step > start_step:
                    last = StepInfo(
                        epoch=(global_step - 1) // ft_spec.steps_per_epoch,
                        epoch_step=(global_step - 1) % ft_spec.steps_per_epoch,
                        global_step=global_step - 1,
                        steps_per_epoch=ft_spec.steps_per_epoch,
                    )
                    recover_handler.graceful_shutdown(
                        actor,
                        last,
                        saver,
                        evaluator,
                        dataloader,
                        stats_logger,
                        tokenizer=tokenizer,
                        config=cfg,
                        rollout=rollout,
                        guard=guard,
                        profiler=profiler,
                        **recover_kwargs,
                    )
                logger.warning(
                    "preemption checkpoint written; exiting %d",
                    PREEMPTION_EXIT_CODE,
                )
                sys.exit(PREEMPTION_EXIT_CODE)

            if guard.should_stop():
                graceful_exit()

            watchdog.beat("rollout")
            profiler_cm = profiler.step(global_step)
            profiler_cm.__enter__()
            # profiler.close() in the finally below finalizes the trace if any
            # step raises mid-window
            timeline.begin_step(global_step)
            with timeline.phase("rollout"), stats_tracker.record_timing(
                "rollout"
            ):
                try:
                    if cfg.async_training:
                        batch = rollout.prepare_batch(dataloader, workflow=workflow)
                    else:
                        batch = rollout.rollout_batch(
                            next(iter(dataloader)), workflow=workflow
                        )
                except RolloutWaitInterrupted:
                    graceful_exit()

            if cfg.actor.recompute_logprob or cfg.actor.use_decoupled_loss:
                with timeline.phase("recompute_logp"), stats_tracker.record_timing(
                    "recompute_logp"
                ):
                    batch["prox_logp"] = actor.actor.compute_logp(batch)

            if ref is not None:
                with timeline.phase("ref_logp"), stats_tracker.record_timing(
                    "ref_logp"
                ):
                    batch["ref_logp"] = ref.compute_logp(batch)

            with timeline.phase("compute_advantage"), stats_tracker.record_timing(
                "compute_advantage"
            ):
                actor.actor.compute_advantages(batch)

            watchdog.beat("train_step")
            with timeline.phase("train_step"), stats_tracker.record_timing(
                "train_step"
            ):
                stats = actor.actor.ppo_update(batch)
                actor.step_lr_scheduler()
            crash_point("post-train-step")

            watchdog.beat("update_weights")
            with timeline.phase("update_weights"), stats_tracker.record_timing(
                "update_weights"
            ):
                rollout.pause()
                actor.update_weights(weight_meta)
                # an unconditional resume would silently undo the
                # sentinel's pause_rollout guardrail one step later
                if health is None or not health.rollout_paused:
                    rollout.resume()

            # sentinel evaluation BEFORE the stats commit and checkpoint:
            # the halt guardrail must preempt both (a poisoned step's dump
            # must never become the resume point); the returned scalars
            # ride this step's stats row
            health_row = (
                health.end_step(global_step, span=timeline.span)
                if health is not None
                else {}
            )

            mean_reward = float(np.mean(np.asarray(batch["rewards"])))
            all_rewards.append(mean_reward)
            # close the attribution window BEFORE the commit so this
            # step's phase breakdown/goodput/MFU ride ITS OWN stats row;
            # the checkpoint below is recorded as a late phase (it rides
            # the train.step span and the flight-recorder entry, and its
            # time_perf/save scalar still exports one step late as before)
            attn = np.asarray(batch["attention_mask"])
            tl_row = timeline.end_step(
                tokens=int(attn.sum()),
                n_seqs=int(attn.shape[0]),
                weight_version=actor.get_version(),
                extra={"profiled": float(profiler.active)},
            )
            stats[0].update(stats_tracker.export(key="time_perf"))
            stats[0].update(tl_row)
            stats[0].update(health_row)
            stats[0]["grpo/mean_task_reward"] = mean_reward
            # commit BEFORE the recover dump: a kill after the dump's
            # marker flips but before the commit would resume at the next
            # step and lose this step's stats row forever; committing
            # first is safe because the resume dedup (the jsonl scan) skips
            # the replayed commit if the dump never lands. Accepted
            # tradeoff: the save/dump timing below is exported one step
            # late (and the last step's is dropped) — crash-exactness of
            # the row beats perfectly attributed checkpoint timing
            stats_logger.commit(
                step_info.epoch, step_info.epoch_step, global_step, stats
            )

            watchdog.beat("save")
            with timeline.phase("checkpoint"), stats_tracker.record_timing(
                "save"
            ):
                saver.save(
                    actor,
                    step_info,
                    tokenizer=tokenizer,
                    protect=recover_handler.protected_paths(**recover_kwargs),
                )
                recover_handler.dump(
                    actor,
                    step_info,
                    saver,
                    evaluator,
                    dataloader,
                    stats_logger,
                    tokenizer=tokenizer,
                    config=cfg,
                    rollout=rollout,
                    **recover_kwargs,
                )

            profiler_cm.__exit__(None, None, None)
    finally:
        # finalize any in-flight profiler trace even when a step dies
        profiler.close()
        timeline.close()  # end the last train.step span + recorder entry
        watchdog.stop()
        guard.uninstall()

    # artifact the e2e test asserts on (reference tests/grpo pattern)
    out = os.path.join(stats_logger.log_dir(), "rewards.json")
    with open(out, "w") as f:
        json.dump(all_rewards, f)
    logger.info("wrote %s", out)

    stats_logger.close()
    if fleet_controller is not None:
        fleet_controller.close()  # reap provider-owned servers (drain grace)
    rollout.destroy()
    actor.destroy()


if __name__ == "__main__":
    main(sys.argv[1:])
