"""Python-executor tool environment (reference: examples/tir/tool_manager.py
capability): runs model-emitted python snippets through the sandboxed
executor (areal_tpu/reward/sandbox.py — rlimits on CPU/memory/files, empty
env, throwaway cwd) and returns stdout as the observation."""

from __future__ import annotations

import asyncio
from typing import Any

from areal_tpu.api.env_api import Environment
from areal_tpu.reward.sandbox import run_sandboxed


class PythonToolEnv(Environment):
    def __init__(self, timeout: float = 10.0):
        self.timeout = timeout

    async def alist_tools(self) -> list[dict[str, Any]]:
        return [
            {
                "type": "function",
                "function": {
                    "name": "python",
                    "description": "Execute python code; stdout is returned.",
                    "parameters": {
                        "type": "object",
                        "properties": {"code": {"type": "string"}},
                        "required": ["code"],
                    },
                },
            }
        ]

    async def aexecute(
        self, tool_name: str, arguments: dict[str, Any], timeout: float | None = None
    ) -> tuple[str, bool]:
        if tool_name != "python":
            return f"unknown tool {tool_name}", False
        code = arguments.get("code", "")
        loop = asyncio.get_running_loop()
        out, ok = await loop.run_in_executor(
            None, lambda: run_sandboxed(code, timeout=timeout or self.timeout)
        )
        return out[-2000:], ok
