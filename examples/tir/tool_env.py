"""Python-executor tool environment (reference: examples/tir/tool_manager.py
capability): runs model-emitted python snippets through the sandboxed
reward-execution plane and returns stdout as the observation.

Execution routes through ``areal_tpu.reward_service.aexecute_code`` — the
configured service client when one is installed (``reward_service.enabled``),
the process-global BOUNDED worker pool otherwise. It must never touch the
event loop's default thread pool: the old ``run_in_executor(None, ...)``
offload meant one batch of wedged sandbox calls exhausted the default
executor and stalled every concurrent workflow's tool calls (pinned by a
regression test and the ``unbounded-default-executor`` lint rule)."""

from __future__ import annotations

from typing import Any

from areal_tpu.api.env_api import Environment


class PythonToolEnv(Environment):
    def __init__(self, timeout: float = 10.0, executor=None):
        self.timeout = timeout
        # injectable async executor (tests); default = the reward plane
        if executor is None:
            from areal_tpu.reward_service import aexecute_code

            async def executor(code: str, timeout: float):
                r = await aexecute_code(code, timeout=timeout)
                return r.output, r.ok

        self._executor = executor

    async def alist_tools(self) -> list[dict[str, Any]]:
        return [
            {
                "type": "function",
                "function": {
                    "name": "python",
                    "description": "Execute python code; stdout is returned.",
                    "parameters": {
                        "type": "object",
                        "properties": {"code": {"type": "string"}},
                        "required": ["code"],
                    },
                },
            }
        ]

    async def aexecute(
        self, tool_name: str, arguments: dict[str, Any], timeout: float | None = None
    ) -> tuple[str, bool]:
        if tool_name != "python":
            return f"unknown tool {tool_name}", False
        code = arguments.get("code", "")
        out, ok = await self._executor(code, timeout or self.timeout)
        return out[-2000:], ok
