"""Python-executor tool environment (reference: examples/tir/tool_manager.py
capability): runs model-emitted python snippets in a subprocess with a
timeout and returns stdout as the observation."""

from __future__ import annotations

import asyncio
import sys
from typing import Any

from areal_tpu.api.env_api import Environment


class PythonToolEnv(Environment):
    def __init__(self, timeout: float = 10.0):
        self.timeout = timeout

    async def alist_tools(self) -> list[dict[str, Any]]:
        return [
            {
                "type": "function",
                "function": {
                    "name": "python",
                    "description": "Execute python code; stdout is returned.",
                    "parameters": {
                        "type": "object",
                        "properties": {"code": {"type": "string"}},
                        "required": ["code"],
                    },
                },
            }
        ]

    async def aexecute(
        self, tool_name: str, arguments: dict[str, Any], timeout: float | None = None
    ) -> tuple[str, bool]:
        if tool_name != "python":
            return f"unknown tool {tool_name}", False
        code = arguments.get("code", "")
        proc = await asyncio.create_subprocess_exec(
            sys.executable,
            "-I",  # isolated mode: no site, no user paths
            "-c",
            code,
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.STDOUT,
        )
        try:
            out, _ = await asyncio.wait_for(
                proc.communicate(), timeout or self.timeout
            )
        except asyncio.TimeoutError:
            proc.kill()
            return "execution timed out", False
        text = out.decode(errors="replace")[-2000:]
        return text, proc.returncode == 0
