"""Tool-integrated-reasoning workflow (reference: examples/tir/tir_workflow.py):
the model interleaves reasoning with ```python ...``` blocks; each block runs
in the PythonToolEnv and its output is spliced back as an observation turn,
up to ``max_tool_calls``; the final answer is scored by the math verifier.
"""

from __future__ import annotations

import re
from typing import Any

from areal_tpu.api.cli_args import GenerationHyperparameters
from areal_tpu.api.reward_api import AsyncRewardWrapper
from areal_tpu.api.workflow_api import RolloutWorkflow
from areal_tpu.workflow.tool_loop import pack_episode, run_tool_episode
from examples.tir.tool_env import PythonToolEnv

_CODE_RE = re.compile(r"```python\s*(.*?)```", re.DOTALL)


class TIRWorkflow(RolloutWorkflow):
    def __init__(
        self,
        reward_fn,
        gconfig: GenerationHyperparameters,
        tokenizer,
        max_tool_calls: int = 3,
        tool_timeout: float = 10.0,
        in_process_reward: bool = False,
        tool_metrics: bool = True,
    ):
        self.reward_fn = AsyncRewardWrapper(reward_fn, in_process=in_process_reward)
        # stop at the end of a code block so the tool can run before the
        # model continues
        self.gconfig = gconfig.new(n_samples=1, stop=list(gconfig.stop) + ["```\n"])
        self.tokenizer = tokenizer
        self.max_tool_calls = max_tool_calls
        self.tool_metrics = tool_metrics
        # sandbox execution routes through the reward plane (service
        # client when reward_service.enabled, bounded pool otherwise)
        self.env = PythonToolEnv(timeout=tool_timeout)

    @classmethod
    def from_config(cls, reward_fn, gconfig, tokenizer, reward_service_cfg,
                    **kw):
        """Build with the workflow knobs from a RewardServiceConfig
        (tool_metrics, task_timeout as the tool deadline)."""
        kw.setdefault("tool_timeout", reward_service_cfg.task_timeout)
        kw.setdefault("tool_metrics", reward_service_cfg.tool_metrics)
        return cls(reward_fn, gconfig, tokenizer, **kw)

    async def arun_episode(self, engine, data: dict[str, Any]):
        prompt_ids = list(
            self.tokenizer.apply_chat_template(
                data["messages"], tokenize=True, add_generation_prompt=True
            )
        )

        def parse(chunk: str):
            codes = _CODE_RE.findall(chunk)
            return codes[-1] if codes else None

        async def execute(code):
            obs, _ok = await self.env.aexecute("python", {"code": code})
            return obs

        seq, loss_mask, logprobs, versions, full_text = await run_tool_episode(
            engine,
            self.tokenizer,
            self.gconfig,
            prompt_ids,
            parse,
            execute,
            lambda obs: f"\n<output>\n{obs}\n</output>\n",
            self.max_tool_calls,
            action_name=lambda _a: "python",
            tool_metrics=self.tool_metrics,
        )
        reward = await self.reward_fn(
            None, full_text, None, None,
            **{k: v for k, v in data.items() if k != "messages"},
        )
        return pack_episode(seq, loss_mask, logprobs, versions, reward)
