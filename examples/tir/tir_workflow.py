"""Tool-integrated-reasoning workflow (reference: examples/tir/tir_workflow.py):
the model interleaves reasoning with ```python ...``` blocks; each block runs
in the PythonToolEnv and its output is spliced back as an observation turn,
up to ``max_tool_calls``; the final answer is scored by the math verifier.
"""

from __future__ import annotations

import re
import uuid
from typing import Any

import numpy as np

from areal_tpu.api.cli_args import GenerationHyperparameters
from areal_tpu.api.io_struct import ModelRequest
from areal_tpu.api.reward_api import AsyncRewardWrapper
from areal_tpu.api.workflow_api import RolloutWorkflow
from areal_tpu.utils.data import concat_padded_tensors
from examples.tir.tool_env import PythonToolEnv

_CODE_RE = re.compile(r"```python\s*(.*?)```", re.DOTALL)


class TIRWorkflow(RolloutWorkflow):
    def __init__(
        self,
        reward_fn,
        gconfig: GenerationHyperparameters,
        tokenizer,
        max_tool_calls: int = 3,
        tool_timeout: float = 10.0,
        in_process_reward: bool = False,
    ):
        self.reward_fn = AsyncRewardWrapper(reward_fn, in_process=in_process_reward)
        # stop at the end of a code block so the tool can run before the
        # model continues
        self.gconfig = gconfig.new(n_samples=1, stop=list(gconfig.stop) + ["```\n"])
        self.tokenizer = tokenizer
        self.max_tool_calls = max_tool_calls
        self.env = PythonToolEnv(timeout=tool_timeout)

    async def arun_episode(self, engine, data: dict[str, Any]):
        seq = list(
            self.tokenizer.apply_chat_template(
                data["messages"], tokenize=True, add_generation_prompt=True
            )
        )
        loss_mask = [0] * len(seq)
        logprobs = [0.0] * len(seq)
        versions = [-1] * len(seq)
        rid = str(uuid.uuid4())
        full_text = ""
        for _ in range(self.max_tool_calls + 1):
            resp = await engine.agenerate(
                ModelRequest(
                    rid=rid, input_ids=list(seq), gconfig=self.gconfig,
                    tokenizer=self.tokenizer,
                )
            )
            seq += resp.output_tokens
            loss_mask += [1] * resp.output_len
            logprobs += resp.output_logprobs
            versions += resp.output_versions
            chunk = self.tokenizer.decode(resp.output_tokens)
            full_text += chunk
            codes = _CODE_RE.findall(chunk)
            if not codes or resp.stop_reason != "stop":
                break
            obs, _ok = await self.env.aexecute("python", {"code": codes[-1]})
            obs_text = f"\n<output>\n{obs}\n</output>\n"
            obs_ids = self.tokenizer.encode(obs_text, add_special_tokens=False)
            seq += obs_ids
            loss_mask += [0] * len(obs_ids)  # tool output is not model policy
            logprobs += [0.0] * len(obs_ids)
            versions += [-1] * len(obs_ids)
            full_text += obs_text

        reward = await self.reward_fn(
            None, full_text, None, None,
            **{k: v for k, v in data.items() if k != "messages"},
        )
        n = len(seq)
        return concat_padded_tensors(
            [
                dict(
                    input_ids=np.asarray(seq, np.int64)[None],
                    loss_mask=np.asarray(loss_mask, np.int64)[None],
                    logprobs=np.asarray(logprobs, np.float32)[None],
                    versions=np.asarray(versions, np.int64)[None],
                    attention_mask=np.ones((1, n), np.int64),
                    rewards=np.asarray([reward], np.float32),
                )
            ]
        )
