"""Benchmark: training + decode throughput on a Qwen2-1.5B-shaped dense
decoder (the reference quickstart model family, examples/math GSM8K configs).
Prints ONE JSON line.

Metrics:
- primary: SFT train tokens/sec/chip on the FULL 28-layer Qwen2-1.5B shape
  (bf16, remat, packed 1D streams) + analytic MFU
  (areal_tpu/utils/perf.py — the realhf/base/monitor.py:288-403 equivalent).
- secondary: continuous-batching decode tokens/sec on the GenerationEngine.

vs_baseline derivation: the reference's H800 throughput numbers normalize to
~40% MFU for a well-tuned dense-1.5B trainer
(benchmark/verl_v0_3_0_post1_76084d3/README.md method). Raw tokens/s are not
comparable across different chips (H800 ~495 dense bf16 TFLOP/s vs e.g.
v5e 197), so vs_baseline = measured_MFU / 0.40 — the hardware-normalized
ratio. The raw tokens/s and chip kind are reported alongside.

Robustness: the TPU backend rides a tunnel that can be transiently
unavailable (round-1 failure mode); backend init retries with diagnostics
before giving up.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

REFERENCE_MFU = 0.40
METRIC = "sft_train_tokens_per_sec_per_chip_qwen2_1.5b"


def log(msg: str):
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


def init_backend(retries: int = 5, sleep_s: float = 20.0, attempt_s: float = 120.0):
    """jax.devices() with retry + diagnostics (backend tunnel can flap).

    Each attempt runs in a daemon thread with a deadline: a wedged tunnel
    BLOCKS inside backend init instead of erroring (observed failure mode),
    and an indefinite hang here would surface as a driver-side timeout with
    no parseable record at all."""
    import threading

    import jax

    last: list = [None]
    attempts_run = 0
    for i in range(retries):
        attempts_run = i + 1
        box: list = []

        def attempt():
            try:
                box.append(jax.devices())
            except Exception as e:  # backend UNAVAILABLE etc.
                last[0] = e

        th = threading.Thread(target=attempt, daemon=True)
        th.start()
        th.join(attempt_s)
        if box:
            log(f"backend={jax.default_backend()} devices={box[0]}")
            return box[0]
        if th.is_alive():
            last[0] = TimeoutError(
                f"backend init still blocked after {attempt_s}s "
                "(tunnel wedged — claim never resolves)"
            )
            # the stuck thread holds jax's init lock; further in-process
            # retries would just queue behind it
            break
        log(f"backend init attempt {i + 1}/{retries} failed: {last[0]}")
        if i + 1 < retries:
            time.sleep(sleep_s)
    raise RuntimeError(
        f"TPU backend unavailable after {attempts_run} attempt(s): {last[0]}"
    )


def qwen2_1p5b_cfg(layers: int = 28):
    from areal_tpu.models.config import TransformerConfig

    return TransformerConfig(
        arch="qwen2",
        vocab_size=151936,
        hidden_size=1536,
        intermediate_size=8960,
        num_hidden_layers=layers,
        num_attention_heads=12,
        num_key_value_heads=2,
        head_dim=128,
        rope_theta=1e6,
        attention_bias=True,
        tie_word_embeddings=True,
    )


def _is_oom(msg: str) -> bool:
    return "RESOURCE_EXHAUSTED" in msg or "out of memory" in msg.lower()


def sft_bench(
    layers: int,
    opt_type: str,
    seqlen: int,
    n_seqs: int,
    remat_policy: str = "nothing_saveable",
    mb_tokens: int | None = None,
    loss_chunk: int = 1024,
):
    """One SFT throughput measurement; returns (tokens/s, mfu or None)."""
    from areal_tpu.api.cli_args import (
        MicroBatchSpec,
        OptimizerConfig,
        TrainEngineConfig,
    )
    from areal_tpu.engine.sft.lm_engine import TPULMEngine
    from areal_tpu.utils import perf

    cfg = TrainEngineConfig(
        path="",
        init_from_scratch=True,
        optimizer=OptimizerConfig(lr=1e-4, type=opt_type),
        mb_spec=MicroBatchSpec(max_tokens_per_mb=mb_tokens or n_seqs * seqlen),
    )
    cfg.backend.remat = True
    cfg.backend.remat_policy = remat_policy
    cfg.backend.pad_mb_to_multiple = 512
    # chunked fused LM head: [T, V] fp32 logits (2.5GB at mb=4096) never
    # materialize, freeing HBM for the lighter remat policies
    cfg.backend.loss_chunk_size = loss_chunk
    # single 16GB chip hosting a 1.5B model: bf16 adam moments + bf16 grad
    # accumulator (multi-chip deployments shard optimizer state over dp
    # instead — parallel/sharding.py fsdp)
    cfg.backend.optimizer_dtype = "bfloat16"
    cfg.backend.grad_acc_dtype = "bfloat16"
    model_cfg = qwen2_1p5b_cfg(layers)
    engine = TPULMEngine(cfg)
    engine.initialize(None, None, model_config=model_cfg)

    rng = np.random.default_rng(0)
    data = dict(
        input_ids=rng.integers(1, 150000, size=(n_seqs, seqlen)).astype(np.int32),
        attention_mask=np.ones((n_seqs, seqlen), np.int32),
        loss_mask=np.ones((n_seqs, seqlen), np.int32),
    )
    data["loss_mask"][:, 0] = 0

    try:
        for _ in range(2):  # compile + warmup
            engine.train_lm(data)
        n_steps = 5
        t0 = time.perf_counter()
        for _ in range(n_steps):
            stats = engine.train_lm(data)
        dt = time.perf_counter() - t0
        assert np.isfinite(stats["loss"]), stats
        tps = n_seqs * seqlen * n_steps / dt
        fpt = perf.train_flops_per_token(model_cfg, seqlen)
        return tps, perf.mfu(tps, fpt)
    finally:
        engine.destroy()


def decode_bench(layers: int = 28, n_requests: int = 64, prompt_len: int = 128,
                 new_tokens: int = 128, batch: int = 48, steps_per_call: int = 32):
    """Continuous-batching decode throughput on the GenerationEngine.

    Decode is HBM-bound (every step re-reads the 3GB bf16 params), so
    aggregate tokens/s scales with concurrent slots until compute-bound;
    the batch value is picked to fit KV + params + logits in 16GB."""
    import threading

    from areal_tpu.api.cli_args import GenerationHyperparameters, JaxGenConfig
    from areal_tpu.inference.engine import GenerationEngine

    model_cfg = qwen2_1p5b_cfg(layers)
    eng = GenerationEngine(
        JaxGenConfig(
            max_batch_size=batch,
            max_seq_len=512,
            prefill_chunk=128,
            # long decode chains amortize per-dispatch latency (the bench
            # tunnel adds ~70ms RTT per host sync; real hosts ~none) at the
            # cost of post-EOS overshoot — fine for fixed-length decode
            decode_steps_per_call=steps_per_call,
            dtype="bfloat16",
        ),
        model_config=model_cfg,
    )
    eng.start()
    try:
        rng = np.random.default_rng(0)
        done = threading.Event()
        results = []
        lock = threading.Lock()

        def cb(r):
            with lock:
                results.append(r)
                if len(results) >= n_requests:
                    done.set()

        gconfig = GenerationHyperparameters(
            max_new_tokens=new_tokens, min_new_tokens=new_tokens, temperature=1.0
        )

        # warmup: compile prefill buckets + decode before the timed window
        warm = threading.Event()
        eng.submit(
            "warm",
            rng.integers(1, 150000, size=prompt_len).tolist(),
            GenerationHyperparameters(
                max_new_tokens=16, min_new_tokens=16, temperature=1.0
            ),
            lambda r: warm.set(),
        )
        assert warm.wait(600), "decode warmup timed out"

        t0 = time.perf_counter()
        for i in range(n_requests):
            prompt = rng.integers(1, 150000, size=prompt_len).tolist()
            eng.submit(f"bench-{i}", prompt, gconfig, cb)
        assert done.wait(1200), "decode bench timed out"
        dt = time.perf_counter() - t0
        total_out = sum(len(r.output_tokens) for r in results)
        return total_out / dt
    finally:
        eng.stop()


def _run_child(kind: str, att: dict, timeout: float = 1500.0):
    """Each measurement runs in a fresh process: a prior OOMed attempt must
    not leave allocations (or exception-frame references) poisoning HBM."""
    import subprocess

    cmd = [sys.executable, __file__, f"--{kind}-child", json.dumps(att)]
    r = subprocess.run(cmd, capture_output=True, text=True, timeout=timeout)
    sys.stderr.write(r.stderr[-2000:])
    if r.returncode != 0:
        tail = (r.stderr or r.stdout)[-1500:]
        if _is_oom(tail):
            raise MemoryError(tail)
        raise RuntimeError(f"{kind} child failed rc={r.returncode}: {tail}")
    return json.loads(r.stdout.strip().splitlines()[-1])


def main():
    devices = init_backend()
    from areal_tpu.utils import perf

    chip = getattr(devices[0], "device_kind", "unknown")
    peak = perf.chip_peak_flops(devices[0])

    # ---- SFT train throughput (primary) ----
    # ladder: full model first (adam OOMs a 16GB chip at 1.5B even with bf16
    # moments -> adafactor); depth reduction is the last resort
    attempts = [
        # 4096-token microbatches hit the chip's matmul sweet spot; grad
        # accumulation over 2 of them amortizes the fixed per-step cost
        # (measured: 4.5k tok/s vs 4.3k single-mb, vs 3.7k one 8192 mb).
        # Lighter remat first: "mlp_saveable" keeps the two FLOPs-dominant
        # projections (~60% less backward recompute for 4.1GB at mb=4096);
        # "dots..." keeps every matmul output (fits at mb=2048). Both fall
        # back to full recompute on OOM.
        dict(layers=28, opt_type="adafactor", seqlen=4096, n_seqs=2,
             mb_tokens=4096,
             remat_policy="dots_with_no_batch_dims_saveable"),
        dict(layers=28, opt_type="adafactor", seqlen=4096, n_seqs=2,
             mb_tokens=4096, remat_policy="mlp_saveable"),
        dict(layers=28, opt_type="adafactor", seqlen=4096, n_seqs=2,
             mb_tokens=4096),
        dict(layers=28, opt_type="adafactor", seqlen=4096, n_seqs=1),
        dict(layers=28, opt_type="adafactor", seqlen=2048, n_seqs=2),
        dict(layers=14, opt_type="adamw", seqlen=2048, n_seqs=2),
        dict(layers=8, opt_type="adamw", seqlen=2048, n_seqs=2),
    ]
    tps = mfu_v = None
    used = None
    for att in attempts:
        try:
            log(f"sft attempt: {att}")
            res = _run_child("sft", att)
            tps, mfu_v = res["tps"], res["mfu"]
            used = att
            break
        except MemoryError:
            log(f"OOM at {att}; falling back")
    if tps is None:
        raise RuntimeError("all sft bench configurations OOMed")

    # ---- decode throughput (secondary) ----
    # decode is HBM-bound on the 3.1GB param read per step, so tokens/s
    # scales ~linearly with concurrent slots until the KV + logits fill
    # HBM — try large batches first, fall back on OOM
    decode_tps = None
    for datt in [
        dict(n_requests=320, batch=160, steps_per_call=64),
        dict(n_requests=192, batch=96, steps_per_call=64),
        dict(n_requests=64, batch=48, steps_per_call=32),
    ]:
        try:
            log(f"decode attempt: {datt}")
            decode_tps = _run_child(
                "decode", dict(layers=used["layers"], **datt)
            )["tps"]
            break
        except Exception as e:
            log(f"decode bench failed at {datt}: {e}")

    out = {
        "metric": METRIC,
        "value": round(tps * used["layers"] / 28.0, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu_v / REFERENCE_MFU, 3) if mfu_v else None,
        "mfu": round(mfu_v, 4) if mfu_v else None,
        "chip": chip,
        "chip_peak_tflops": peak / 1e12 if peak else None,
        "layers_used": used["layers"],
        "seqlen": used["seqlen"],
        "optimizer": used["opt_type"],
        "raw_tokens_per_sec": round(tps, 1),
        "decode_tokens_per_sec": round(decode_tps, 1) if decode_tps else None,
    }
    print(json.dumps(out))


def _child_main():
    kind = sys.argv[1]
    att = json.loads(sys.argv[2])
    if kind == "--sft-child":
        tps, mfu_v = sft_bench(**att)
        print(json.dumps({"tps": tps, "mfu": mfu_v}))
    elif kind == "--decode-child":
        print(json.dumps({"tps": decode_bench(**att)}))
    else:
        raise SystemExit(f"unknown child kind {kind}")


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1].endswith("-child"):
        _child_main()
    else:
        try:
            main()
        except Exception as e:  # backend outage etc. — emit a parseable
            # record instead of only a stack trace (round-1 failure mode:
            # the tunnel flapped and the driver recorded parsed:null)
            print(
                json.dumps(
                    {
                        "metric": METRIC,
                        "value": None,
                        "unit": "tokens/s",
                        "vs_baseline": None,
                        "error": str(e)[:500],
                    }
                )
            )
            raise
