"""Benchmark: SFT training throughput (tokens/sec/chip) on a Qwen2-1.5B-shaped
dense decoder — the reference quickstart model family (examples/math GSM8K
configs). Prints ONE JSON line.

vs_baseline derivation: the reference trains on H800 GPUs; a well-tuned dense
1.5B Megatron/FSDP trainer reaches ~40% MFU of H800's ~495 TFLOP/s dense bf16
=> 0.4*495e12 / (6*1.5e9) ~= 22,000 tokens/s/GPU. vs_baseline is measured
tokens/s/chip divided by that hardware-normalized reference estimate.
"""

from __future__ import annotations

import json
import time

import numpy as np

BASELINE_TOKENS_PER_SEC = 22000.0


def make_cfg(layers: int):
    from areal_tpu.models.config import TransformerConfig

    return TransformerConfig(
        arch="qwen2",
        vocab_size=151936,
        hidden_size=1536,
        intermediate_size=8960,
        num_hidden_layers=layers,
        num_attention_heads=12,
        num_key_value_heads=2,
        head_dim=128,
        rope_theta=1e6,
        attention_bias=True,
        tie_word_embeddings=True,
    )


def run(layers: int, seqlen: int = 2048, n_seqs: int = 4):
    from areal_tpu.api.cli_args import (
        MicroBatchSpec,
        OptimizerConfig,
        TrainEngineConfig,
    )
    from areal_tpu.engine.sft.lm_engine import TPULMEngine

    cfg = TrainEngineConfig(
        path="",
        init_from_scratch=True,
        optimizer=OptimizerConfig(lr=1e-4),
        mb_spec=MicroBatchSpec(max_tokens_per_mb=n_seqs * seqlen),
    )
    cfg.backend.remat = True
    cfg.backend.pad_mb_to_multiple = 512
    engine = TPULMEngine(cfg)
    engine.initialize(None, None, model_config=make_cfg(layers))

    rng = np.random.default_rng(0)
    data = dict(
        input_ids=rng.integers(1, 150000, size=(n_seqs, seqlen)).astype(np.int32),
        attention_mask=np.ones((n_seqs, seqlen), np.int32),
        loss_mask=np.ones((n_seqs, seqlen), np.int32),
    )
    data["loss_mask"][:, 0] = 0

    for _ in range(2):  # warmup + compile
        engine.train_lm(data)
    n_steps = 5
    t0 = time.perf_counter()
    for _ in range(n_steps):
        stats = engine.train_lm(data)
    dt = time.perf_counter() - t0
    assert np.isfinite(stats["loss"])
    return n_seqs * seqlen * n_steps / dt


def main():
    tps, layers_used = None, None
    for layers in (28, 14, 8):
        try:
            tps = run(layers)
            layers_used = layers
            break
        except Exception as e:  # OOM on small chips -> shrink depth
            msg = str(e)
            if "RESOURCE_EXHAUSTED" not in msg and "Out of memory" not in msg.lower():
                raise
    if tps is None:
        raise RuntimeError("benchmark failed at all model sizes")
    # normalize to the full 28-layer model's per-token cost if we had to shrink
    scale = layers_used / 28.0
    eff_tps = tps * scale
    print(
        json.dumps(
            {
                "metric": "sft_train_tokens_per_sec_per_chip_qwen2_1.5b",
                "value": round(eff_tps, 1),
                "unit": "tokens/s",
                "vs_baseline": round(eff_tps / BASELINE_TOKENS_PER_SEC, 3),
                "layers_used": layers_used,
                "raw_tokens_per_sec": round(tps, 1),
            }
        )
    )


if __name__ == "__main__":
    main()
