"""Benchmark: training + decode throughput on a Qwen2-1.5B-shaped dense
decoder (the reference quickstart model family, examples/math GSM8K configs).

Output: one JSON line per completed rung, with the PRIMARY metric printed
LAST (and mirrored to BENCH_PARTIAL.jsonl as rungs complete, so a mid-run
kill still leaves a record).

Rungs, in order:
1. pallas_kernel_validation — compile (NOT interpret) the flash-attention
   kernel fwd+bwd at block 128/256 on 8k/32k packed streams, plus the
   ring-CP and ulysses wrappers, on the real backend. De-risks every other
   number in the repo (round-2 verdict: kernels had only ever run in
   interpret mode).
2. sft_train_tokens_per_sec_per_chip_qwen2_1.5b (PRIMARY) — full 28-layer
   SFT throughput ladder (bf16, remat, packed 1D streams) + analytic MFU.
1.5. paged_decode_attention — the ragged paged-attention Pallas decode
   kernel vs the XLA gather path (step latency + e2e tokens/s, greedy
   output identity asserted in-child).
1.6. chunked_prefill_attention — the chunked-prefill flash kernel vs the
   XLA gather path at Tq > 1 (step latency + e2e chunked-warming
   tokens/s, greedy identity asserted in-child).
1.7. kv_quant_decode — int8 KV-quantized Pallas decode (in-kernel dequant)
   vs the XLA dequant-gather path, same bars.
3. decode_tokens_per_sec — continuous-batching decode on GenerationEngine.
4. grpo_step_sec — one full async-RL GRPO step (rollout + train + weight
   push) with the colocated engine; the reference's headline metric is
   step time, not SFT throughput.

vs_baseline derivation (primary): the reference's H800 numbers normalize to
~40% MFU for a well-tuned dense-1.5B trainer
(benchmark/verl_v0_3_0_post1_76084d3/README.md method). Raw tokens/s are
not comparable across chips (H800 ~495 dense bf16 TFLOP/s vs v5e 197), so
vs_baseline = measured_MFU / 0.40 — the hardware-normalized ratio.

Tunnel robustness (round-1 AND round-2 failure mode: the TPU tunnel wedges
such that backend init BLOCKS forever instead of erroring): this parent
process NEVER imports jax. Every backend touch — the liveness probe and
every measurement — runs in a freshly exec'd subprocess with a hard
timeout; a wedged child is killed and retried with exponential backoff
until the wall budget (AREAL_BENCH_WALL_S, default 6000s) is spent. A
stuck in-process thread would hold jax's init lock forever; a killed
subprocess releases its tunnel claim.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REFERENCE_MFU = 0.40
METRIC = "sft_train_tokens_per_sec_per_chip_qwen2_1.5b"
REPO = os.path.dirname(os.path.abspath(__file__))
# Rehearsal mode (AREAL_BENCH_REHEARSAL=1): run the WHOLE ladder on CPU with
# scaled-down shapes to prove the mechanics — every rung completes, emits its
# record, and no single child can eat the window (the round-4 failure mode).
# Records go to a separate file so a rehearsal never pollutes the real
# hardware artifact.
REHEARSAL = os.environ.get("AREAL_BENCH_REHEARSAL") == "1"
PARTIAL_PATH = os.path.join(
    REPO, "BENCH_REHEARSAL.jsonl" if REHEARSAL else "BENCH_PARTIAL.jsonl"
)

WALL_S = float(os.environ.get("AREAL_BENCH_WALL_S", "6000"))
_T0 = time.time()
# one id per bench invocation, stamped on every emitted record: the
# rehearsal file is an APPENDED trajectory (the perf-regression sentinel
# groups and compares runs), not a per-run scratch file
RUN_ID = f"{int(_T0)}-{os.getpid()}"


def log(msg: str):
    print(f"[bench +{time.time() - _T0:7.1f}s] {msg}", file=sys.stderr, flush=True)


def remaining(deadline: float) -> float:
    return deadline - time.time()


def emit(record: dict):
    """One metric line on stdout + append to the partial file."""
    if REHEARSAL:
        record = {**record, "rehearsal": True}
    record = {**record, "run_id": RUN_ID, "ts": round(time.time(), 3)}
    line = json.dumps(record)
    print(line, flush=True)
    try:
        with open(PARTIAL_PATH, "a") as f:
            f.write(line + "\n")
    except OSError:
        pass


def emit_wedged(metric: str, phase: str, timeout_s: float | None):
    """Wedge forensics: a rung child that TIMED OUT (the rc=124 tunnel
    failure mode — backend init blocks instead of erroring) records a
    structured artifact instead of leaving nothing. The sentinel treats
    wedged records as "no data": never a regression, never a baseline
    sample."""
    emit(
        {
            "metric": metric,
            "value": None,
            "unit": "wedged",
            "vs_baseline": None,
            "wedged": True,
            "phase": phase,
            "timeout_s": round(float(timeout_s), 1) if timeout_s else None,
        }
    )


def note_rung_failure(metric: str, phase: str, e: Exception):
    """Shared rung-failure bookkeeping: log always; emit the wedge
    artifact when the failure was a child timeout."""
    log(f"{phase} rung failed: {e}")
    if isinstance(e, subprocess.TimeoutExpired):
        emit_wedged(metric, phase, getattr(e, "timeout", None))


class BackendWedged(RuntimeError):
    """The backend probe never resolved within the wall budget (the
    BENCH_r0*.json rc=124 signature)."""


def _load_regression_module():
    """Load areal_tpu/bench/regression.py BY PATH: the parent process
    must never import the areal_tpu package (its __init__ pulls jax, and
    a wedged tunnel holds jax's init lock forever)."""
    import importlib.util

    path = os.path.join(REPO, "areal_tpu", "bench", "regression.py")
    spec = importlib.util.spec_from_file_location(
        "areal_tpu_bench_regression", path
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def append_rehearsal_verdict(path: str = None):
    """Self-compare this rehearsal run against its predecessors and
    append one sentinel verdict line to the trajectory. Best-effort: a
    sentinel bug must not fail the bench."""
    try:
        reg = _load_regression_module()
        target = path or PARTIAL_PATH
        report = reg.analyze_file(target)
        reg.append_verdict(target, report, run_id=RUN_ID)
        log(reg.render_text(report))
        return report
    except Exception as e:  # noqa: BLE001
        log(f"sentinel self-compare failed: {e}")
        return None


# ---------------------------------------------------------------------------
# Subprocess plumbing — every jax touch lives in a child
# ---------------------------------------------------------------------------


def _is_oom(msg: str) -> bool:
    return "RESOURCE_EXHAUSTED" in msg or "out of memory" in msg.lower()


def _is_outage(msg: str) -> bool:
    """Backend/tunnel outage signatures — conditions of the CHIP, not of
    the measurement config that happened to hit them."""
    return (
        "UNAVAILABLE" in msg
        or "Unable to initialize backend" in msg
        or "DEADLINE_EXCEEDED" in msg
    )


def _run_child(kind: str, att: dict, timeout: float):
    """Run one measurement in a fresh process: prior OOM must not poison
    HBM, and a wedged tunnel must be killable (an in-process hang would
    hold jax's init lock for the rest of the run)."""
    cmd = [sys.executable, __file__, f"--{kind}-child", json.dumps(att)]
    env = dict(os.environ)
    if REHEARSAL:
        env["AREAL_PLATFORM"] = "cpu"
    r = subprocess.run(
        cmd, capture_output=True, text=True, timeout=timeout, cwd=REPO, env=env
    )
    sys.stderr.write(r.stderr[-2000:])
    if r.returncode != 0:
        tail = (r.stderr or r.stdout)[-1500:]
        if _is_oom(tail):
            raise MemoryError(tail)
        raise RuntimeError(f"{kind} child failed rc={r.returncode}: {tail}")
    return json.loads(r.stdout.strip().splitlines()[-1])


def probe_backend(deadline: float) -> dict:
    """Fight the tunnel for as long as the wall budget allows.

    Each attempt execs a fresh python that inits the backend and runs one
    tiny jitted matmul; a wedge (init blocks) is a TimeoutExpired -> child
    killed -> backoff -> retry. Returns {device_kind, platform, n,
    peak_flops, t_init}."""
    backoff = 20.0
    attempt = 0
    last_err = "no attempt ran"
    while remaining(deadline) > 90:
        attempt += 1
        per_attempt = min(300.0, max(120.0, remaining(deadline) - 60))
        log(f"backend probe attempt {attempt} (timeout {per_attempt:.0f}s)")
        try:
            res = _run_child("probe", {}, timeout=per_attempt)
            log(
                f"backend live: {res['platform']} {res['device_kind']} "
                f"x{res['n']} (init {res['t_init']:.1f}s, attempt {attempt})"
            )
            res["probe_attempts"] = attempt
            return res
        except subprocess.TimeoutExpired:
            last_err = (
                f"probe blocked >{per_attempt:.0f}s (tunnel wedged — claim "
                "never resolves)"
            )
        except (RuntimeError, MemoryError) as e:
            last_err = str(e)[-300:]
        log(f"probe attempt {attempt} failed: {last_err}")
        pause = min(backoff, max(0.0, remaining(deadline) - 120))
        if pause > 0:
            time.sleep(pause)
        backoff = min(backoff * 1.6, 240.0)
    raise BackendWedged(
        f"TPU backend unavailable after {attempt} probe attempt(s) over "
        f"{WALL_S:.0f}s wall budget: {last_err}"
    )


# ---------------------------------------------------------------------------
# Child bodies (these DO import jax — fresh process each)
# ---------------------------------------------------------------------------


def probe_child():
    import jax
    import jax.numpy as jnp

    t0 = time.time()
    devices = jax.devices()
    x = jnp.ones((256, 256), jnp.bfloat16)
    # one-shot device warmup  # arealint: disable-next-line=jit-per-call
    jax.jit(lambda a: a @ a)(x).block_until_ready()
    from areal_tpu.utils import perf

    return {
        "device_kind": getattr(devices[0], "device_kind", "unknown"),
        "platform": jax.default_backend(),
        "n": len(devices),
        "peak_flops": perf.chip_peak_flops(devices[0]),
        "t_init": time.time() - t0,
    }


KERNEL_CONFIGS = [
    dict(name="fwd_bwd_b128_t8k", block=128, t=8192, bwd=True),
    dict(name="fwd_bwd_b256_t8k", block=256, t=8192, bwd=True),
    dict(name="fwd_bwd_b128_t32k", block=128, t=32768, bwd=True),
    dict(name="fwd_b128_t32k_window4k", block=128, t=32768, bwd=False,
         window=4096),
    dict(name="ring_cp_b128_t8k", block=128, t=8192, bwd=True, ring=True),
    dict(name="ulysses_b128_t8k", block=128, t=8192, bwd=True,
         ulysses=True),
    # the serving kernels (paged pool + scalar-prefetch block tables):
    # int8 decode with in-kernel dequant, and the chunked-prefill flash
    # kernel at a full chunk
    dict(name="paged_decode_int8", paged="decode", int8=True, tq=1,
         batch=8, bs=64, nbt=8),
    dict(name="chunked_prefill_t256", paged="prefill", tq=256,
         batch=4, bs=64, nbt=8),
]

# same rung structure, CPU-sized (interpret=True — Pallas cannot compile on
# the CPU backend; the rehearsal proves the ladder, the live run proves the
# kernel)
KERNEL_CONFIGS_REHEARSAL = [
    dict(name="fwd_bwd_b128_t1k", block=128, t=1024, bwd=True,
         interpret=True),
    dict(name="fwd_b128_t2k_window512", block=128, t=2048, bwd=False,
         window=512, interpret=True),
    dict(name="ring_cp_b128_t1k", block=128, t=1024, bwd=True, ring=True,
         interpret=True),
    dict(name="ulysses_b128_t1k", block=128, t=1024, bwd=True, ulysses=True,
         interpret=True),
    dict(name="paged_decode_int8", paged="decode", int8=True, tq=1,
         batch=2, bs=16, nbt=4, interpret=True),
    dict(name="chunked_prefill_t32", paged="prefill", tq=32,
         batch=2, bs=16, nbt=4, interpret=True),
]


def kernels_child(configs: list[dict] | None = None):
    """Compile (non-interpret) + execute the Pallas flash kernel fwd+bwd and
    the ring/ulysses wrappers on the real backend; per-config pass/fail."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from areal_tpu.ops.pallas.flash_attention import flash_attention_packed

    configs = configs or KERNEL_CONFIGS
    nh, kh, d = 12, 2, 128
    results = {}
    for c in configs:
        if c.get("paged"):
            results[c["name"]] = _validate_paged_kernel(c, nh, kh, d)
            continue
        t = c["t"]
        key = jax.random.PRNGKey(0)
        kq, kk, kv = jax.random.split(key, 3)
        q = jax.random.normal(kq, (t, nh, d), jnp.bfloat16)
        k = jax.random.normal(kk, (t, kh, d), jnp.bfloat16)
        v = jax.random.normal(kv, (t, kh, d), jnp.bfloat16)
        # packed stream of 1k-token segments (the varlen case the kernel's
        # block skipping exists for)
        seg = jnp.asarray(np.arange(t) // 1024, jnp.int32)
        try:
            t0 = time.time()
            if c.get("ring") or c.get("ulysses"):
                from jax.sharding import Mesh

                from areal_tpu.ops.ring_attention import ring_attention_sharded
                from areal_tpu.ops.ulysses import ulysses_attention_sharded

                mesh = Mesh(np.array(jax.devices()[:1]).reshape(1), ("cp",))
                wrapper = (
                    ring_attention_sharded if c.get("ring")
                    else ulysses_attention_sharded
                )

                impl = (
                    "pallas_interpret" if c.get("interpret") else "pallas"
                )

                def loss(q, k, v):
                    o = wrapper(
                        mesh, q, k, v, seg, token_axes=("cp",),
                        chunk_impl=impl, block=c["block"],
                    )
                    return jnp.sum(o.astype(jnp.float32) ** 2)

                # per-config compile IS the validation being benchmarked
                # arealint: disable-next-line=jit-in-loop,jit-per-call
                val, grads = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2)))(
                    q, k, v
                )
                jax.block_until_ready((val, grads))
                finite = bool(jnp.isfinite(val))
            elif c.get("bwd"):

                def loss(q, k, v):
                    o = flash_attention_packed(
                        q, k, v, seg, block=c["block"],
                        window=c.get("window", 0),
                        interpret=c.get("interpret", False),
                    )
                    return jnp.sum(o.astype(jnp.float32) ** 2)

                # per-config compile IS the validation being benchmarked
                # arealint: disable-next-line=jit-in-loop,jit-per-call
                val, grads = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2)))(
                    q, k, v
                )
                jax.block_until_ready((val, grads))
                finite = bool(jnp.isfinite(val))
            else:
                # arealint: disable-next-line=jit-in-loop,jit-per-call
                o = jax.jit(
                    lambda q, k, v: flash_attention_packed(
                        q, k, v, seg, block=c["block"],
                        window=c.get("window", 0),
                        interpret=c.get("interpret", False),
                    )
                )(q, k, v)
                jax.block_until_ready(o)
                finite = bool(jnp.isfinite(jnp.sum(o.astype(jnp.float32))))
            dt = time.time() - t0
            assert finite, c
            results[c["name"]] = {"ok": True, "compile_plus_run_s": round(dt, 1)}
        except Exception as e:  # noqa: BLE001 — record per-config failures
            results[c["name"]] = {"ok": False, "error": str(e)[-400:]}
    return results


def _validate_paged_kernel(c: dict, nh: int, kh: int, d: int) -> dict:
    """One pallas_kernel_validation config for the SERVING kernels: compile
    (non-interpret on TPU) + execute the paged decode kernel (int8
    in-kernel dequant variant) or the chunked-prefill flash kernel on a
    churned block table, per-config pass/fail like the flash configs."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    try:
        t0 = time.time()
        interpret = c.get("interpret", False)
        batch, bs, nbt, tq = c["batch"], c["bs"], c["nbt"], c["tq"]
        nb = batch * nbt + 1
        rng = np.random.default_rng(0)
        dt = jnp.float32 if interpret else jnp.bfloat16
        q = jnp.asarray(rng.normal(size=(batch, tq, nh, d)), dt)
        tbl = jnp.asarray(
            rng.permutation(nb - 1)[: batch * nbt].reshape(batch, nbt) + 1,
            jnp.int32,
        )
        lens = jnp.asarray(
            rng.integers(tq, nbt * bs, size=batch), jnp.int32
        )
        kw = {}
        if c.get("int8"):
            from areal_tpu.models.lm import quantize_kv_rows

            rows = jnp.asarray(
                rng.normal(size=(nb, bs, kh, d)), jnp.float32
            )
            kp, kw["k_scale"] = quantize_kv_rows(rows)
            vp, kw["v_scale"] = quantize_kv_rows(rows[::-1])
        else:
            kp = jnp.asarray(rng.normal(size=(nb, bs, kh, d)), dt)
            vp = jnp.asarray(rng.normal(size=(nb, bs, kh, d)), dt)
        if c["paged"] == "prefill":
            from areal_tpu.ops.pallas.chunked_prefill import (
                chunked_prefill_attention as fn,
            )
        else:
            from areal_tpu.ops.pallas.paged_attention import (
                paged_decode_attention as fn,
            )
        # per-config compile IS the validation being benchmarked
        # arealint: disable-next-line=jit-in-loop,jit-per-call
        o = jax.jit(
            lambda q, kp, vp, tbl, lens: fn(
                q, kp, vp, tbl, lens, interpret=interpret, **kw
            )
        )(q, kp, vp, tbl, lens)
        jax.block_until_ready(o)
        finite = bool(jnp.isfinite(jnp.sum(o.astype(jnp.float32))))
        assert finite, c
        return {"ok": True, "compile_plus_run_s": round(time.time() - t0, 1)}
    except Exception as e:  # noqa: BLE001 — record per-config failures
        return {"ok": False, "error": str(e)[-400:]}


def qwen2_1p5b_cfg(layers: int = 28, vocab: int = 151936):
    from areal_tpu.models.config import TransformerConfig

    return TransformerConfig(
        arch="qwen2",
        vocab_size=vocab,
        hidden_size=1536,
        intermediate_size=8960,
        num_hidden_layers=layers,
        num_attention_heads=12,
        num_key_value_heads=2,
        head_dim=128,
        rope_theta=1e6,
        attention_bias=True,
        tie_word_embeddings=True,
    )


def sft_bench(
    layers: int,
    opt_type: str,
    seqlen: int,
    n_seqs: int,
    remat_policy: str = "nothing_saveable",
    mb_tokens: int | None = None,
    loss_chunk: int = 1024,
    vocab: int = 151936,
):
    """One SFT throughput measurement; returns (tokens/s, mfu or None)."""
    import numpy as np

    from areal_tpu.api.cli_args import (
        MicroBatchSpec,
        OptimizerConfig,
        TrainEngineConfig,
    )
    from areal_tpu.engine.sft.lm_engine import TPULMEngine
    from areal_tpu.utils import perf

    cfg = TrainEngineConfig(
        path="",
        init_from_scratch=True,
        optimizer=OptimizerConfig(lr=1e-4, type=opt_type),
        mb_spec=MicroBatchSpec(max_tokens_per_mb=mb_tokens or n_seqs * seqlen),
    )
    cfg.backend.remat = True
    cfg.backend.remat_policy = remat_policy
    cfg.backend.pad_mb_to_multiple = 512
    # chunked fused LM head: [T, V] fp32 logits (2.5GB at mb=4096) never
    # materialize, freeing HBM for the lighter remat policies
    cfg.backend.loss_chunk_size = loss_chunk
    # single 16GB chip hosting a 1.5B model: bf16 adam moments + bf16 grad
    # accumulator (multi-chip deployments shard optimizer state over dp
    # instead — parallel/sharding.py fsdp)
    cfg.backend.optimizer_dtype = "bfloat16"
    cfg.backend.grad_acc_dtype = "bfloat16"
    model_cfg = qwen2_1p5b_cfg(layers, vocab=vocab)
    engine = TPULMEngine(cfg)
    engine.initialize(None, None, model_config=model_cfg)

    rng = np.random.default_rng(0)
    data = dict(
        input_ids=rng.integers(1, vocab - 2, size=(n_seqs, seqlen)).astype(np.int32),
        attention_mask=np.ones((n_seqs, seqlen), np.int32),
        loss_mask=np.ones((n_seqs, seqlen), np.int32),
    )
    data["loss_mask"][:, 0] = 0

    try:
        for _ in range(2):  # compile + warmup
            engine.train_lm(data)
        n_steps = 5
        t0 = time.perf_counter()
        for _ in range(n_steps):
            stats = engine.train_lm(data)
        dt = time.perf_counter() - t0
        assert np.isfinite(stats["loss"]), stats
        tps = n_seqs * seqlen * n_steps / dt
        fpt = perf.train_flops_per_token(model_cfg, seqlen)
        return tps, perf.mfu(tps, fpt)
    finally:
        engine.destroy()


def decode_bench(layers: int = 28, n_requests: int = 64, prompt_len: int = 128,
                 new_tokens: int = 128, batch: int = 48, steps_per_call: int = 32,
                 vocab: int = 151936, max_seq_len: int = 512,
                 spec_decode: str = "none", spec_draft_len: int = 4,
                 repetitive: bool = False, greedy: bool = False,
                 tracing: bool = False):
    """Continuous-batching decode throughput on the GenerationEngine.

    Decode is HBM-bound (every step re-reads the 3GB bf16 params), so
    aggregate tokens/s scales with concurrent slots until compute-bound;
    the batch value is picked to fit KV + params + logits in 16GB.

    ``spec_decode="ngram"`` turns on draft-free speculative decoding;
    ``repetitive=True`` tiles each prompt from a short random base so the
    n-gram proposer has structure to latch onto (the reasoning/math
    regime), and ``greedy=True`` makes acceptance deterministic.
    ``tracing=True`` enables the PR 8 tracing plane end to end (a span
    per request with engine-internal events), for the tracing_overhead
    rung. Returns {"tps", "spec_acceptance_rate", "spec_steps",
    "ttft_mean_s", "output_digest"}."""
    import threading

    import numpy as np

    from areal_tpu.api.cli_args import (
        GenerationHyperparameters,
        JaxGenConfig,
        TracingConfig,
    )
    from areal_tpu.inference.engine import GenerationEngine

    model_cfg = qwen2_1p5b_cfg(layers, vocab=vocab)
    eng = GenerationEngine(
        JaxGenConfig(
            max_batch_size=batch,
            max_seq_len=max_seq_len,
            prefill_chunk=128,
            # long decode chains amortize per-dispatch latency (the bench
            # tunnel adds ~70ms RTT per host sync; real hosts ~none) at the
            # cost of post-EOS overshoot — fine for fixed-length decode
            decode_steps_per_call=steps_per_call,
            dtype="bfloat16",
            spec_decode=spec_decode,
            spec_draft_len=spec_draft_len,
            tracing=TracingConfig(enabled=tracing, service="bench"),
        ),
        model_config=model_cfg,
    )
    eng.start()
    try:
        rng = np.random.default_rng(0)
        done = threading.Event()
        results = []
        lock = threading.Lock()

        def cb(r):
            with lock:
                results.append(r)
                if len(results) >= n_requests:
                    done.set()

        def make_prompt():
            if repetitive:
                base = rng.integers(
                    1, vocab - 2, size=max(4, prompt_len // 8)
                ).tolist()
                return (base * (prompt_len // len(base) + 1))[:prompt_len]
            return rng.integers(1, vocab - 2, size=prompt_len).tolist()

        gconfig = GenerationHyperparameters(
            max_new_tokens=new_tokens, min_new_tokens=new_tokens,
            temperature=1.0, greedy=greedy,
        )

        # warmup: compile prefill buckets + decode before the timed window
        warm = threading.Event()
        eng.submit(
            "warm",
            make_prompt(),
            GenerationHyperparameters(
                max_new_tokens=16, min_new_tokens=16, temperature=1.0,
                greedy=greedy,
            ),
            lambda r: warm.set(),
        )
        assert warm.wait(600), "decode warmup timed out"

        t0 = time.perf_counter()
        for i in range(n_requests):
            kw = {}
            if tracing:
                # mirror the server path: one request span, ended on done
                span = eng._tracer.span("bench.generate", rid=f"bench-{i}")
                kw["span"] = span

                def cb_traced(r, _s=span):
                    _s.end()
                    cb(r)

                eng.submit(f"bench-{i}", make_prompt(), gconfig, cb_traced, **kw)
            else:
                eng.submit(f"bench-{i}", make_prompt(), gconfig, cb)
        assert done.wait(1200), "decode bench timed out"
        dt = time.perf_counter() - t0
        total_out = sum(len(r.output_tokens) for r in results)
        # greedy identity across tracing on/off is the rung's correctness
        # gate: digest is order-independent (keyed by the prompt)
        import hashlib

        dig = hashlib.blake2b(digest_size=16)
        for r in sorted(
            results, key=lambda r: tuple(r.input_tokens)
        ):
            dig.update(np.asarray(r.input_tokens, np.int64).tobytes())
            dig.update(np.asarray(r.output_tokens, np.int64).tobytes())
        ttfts = [r.ttft for r in results if r.ttft]
        return {
            "tps": total_out / dt,
            "spec_acceptance_rate": eng.spec_acceptance_rate,
            "spec_steps": eng.spec_steps_total,
            "ttft_mean_s": float(sum(ttfts) / max(1, len(ttfts))),
            "output_digest": dig.hexdigest(),
        }
    finally:
        eng.stop()


def paged_decode_bench(layers: int = 2, vocab: int = 2048, batch: int = 8,
                       prompt_len: int = 64, new_tokens: int = 32,
                       n_requests: int = 8, page_size: int = 16,
                       max_seq_len: int = 256, steps_per_call: int = 8,
                       kernel_iters: int = 10):
    """Ragged paged-attention decode: Pallas kernel vs the XLA gather path
    (ops/pallas/paged_attention.py vs _pool_view + decode_attention_xla).

    Two measurements:

    1. **raw kernel step latency** — one decode-attention step on a
       pool/table shaped like the serving engine's (qwen2 heads: 12q/2kv,
       d=128; ragged lengths spanning empty to near-full), pallas vs XLA,
       jitted, mean over ``kernel_iters``;
    2. **e2e decode tokens/s** — the same greedy workload through
       GenerationEngine with ``use_pallas_decode`` on vs off, and the
       acceptance bar asserted hard in-child: greedy outputs must be
       TOKEN-IDENTICAL kernel-on vs kernel-off (a speedup measured on
       diverging outputs would be a KV bug, not a win).

    On CPU the kernel runs in interpret mode — the rehearsal proves
    mechanics + parity, not speed (interpret unrolls the grid; expect
    speedup < 1 there; the compiled TPU run is the perf signal)."""
    import threading

    import jax as _jax
    import jax.numpy as jnp
    import numpy as np

    from areal_tpu.api.cli_args import GenerationHyperparameters, JaxGenConfig
    from areal_tpu.inference.engine import GenerationEngine
    from areal_tpu.ops.attention import decode_attention_xla
    from areal_tpu.ops.pallas.paged_attention import paged_decode_attention

    interpret = _jax.default_backend() != "tpu"

    # --- raw kernel: one decode step off a churned pool ---
    nh, kh, d = 12, 2, 128
    bs = page_size
    nbt = max_seq_len // page_size
    nb = batch * nbt + 1
    rng = np.random.default_rng(0)
    dt = jnp.float32 if interpret else jnp.bfloat16
    q = jnp.asarray(rng.normal(size=(batch, 1, nh, d)), dt)
    kp = jnp.asarray(rng.normal(size=(nb, bs, kh, d)), dt)
    vp = jnp.asarray(rng.normal(size=(nb, bs, kh, d)), dt)
    tbl = jnp.asarray(
        rng.permutation(nb - 1)[: batch * nbt].reshape(batch, nbt) + 1,
        jnp.int32,
    )
    lens = jnp.asarray(
        rng.integers(1, max_seq_len, size=batch), jnp.int32
    )

    def xla_step(q, kp, vp, tbl, lens):
        view_k = kp[tbl].reshape(batch, nbt * bs, kh, d)
        view_v = vp[tbl].reshape(batch, nbt * bs, kh, d)
        return decode_attention_xla(q, view_k, view_v, lens)

    def pallas_step(q, kp, vp, tbl, lens):
        return paged_decode_attention(
            q, kp, vp, tbl, lens, interpret=interpret
        )

    def time_step(fn):
        # compile outside the timed window
        # arealint: disable-next-line=jit-in-loop,jit-per-call
        jf = _jax.jit(fn)
        _jax.block_until_ready(jf(q, kp, vp, tbl, lens))
        t0 = time.perf_counter()
        for _ in range(kernel_iters):
            out = jf(q, kp, vp, tbl, lens)
        _jax.block_until_ready(out)
        return (time.perf_counter() - t0) / kernel_iters

    xla_lat = time_step(xla_step)
    pallas_lat = time_step(pallas_step)

    # --- e2e: the engine knob, greedy identity asserted ---
    model_cfg = qwen2_1p5b_cfg(layers, vocab=vocab)
    prompts = [
        rng.integers(1, vocab - 2, size=prompt_len).tolist()
        for _ in range(n_requests)
    ]
    gconfig = GenerationHyperparameters(
        max_new_tokens=new_tokens, min_new_tokens=new_tokens, greedy=True,
    )

    def run_mode(use_pallas: bool):
        eng = GenerationEngine(
            JaxGenConfig(
                max_batch_size=batch,
                max_seq_len=max_seq_len,
                prefill_chunk=64,
                page_size=page_size,
                decode_steps_per_call=steps_per_call,
                # f32 so the identity assert sees no bf16 argmax-tie noise
                dtype="float32",
                use_pallas_decode=use_pallas,
            ),
            model_config=model_cfg,
        )
        eng.start()
        try:
            done = threading.Event()
            results: dict = {}
            lock = threading.Lock()

            def cb(i, r):
                with lock:
                    results[i] = r
                    if len(results) >= n_requests:
                        done.set()

            t0 = time.perf_counter()
            for i, p in enumerate(prompts):
                eng.submit(
                    f"pd{i}", list(p), gconfig,
                    lambda r, i=i: cb(i, r),
                )
            assert done.wait(1200), "paged-decode bench timed out"
            wall = time.perf_counter() - t0
            toks = sum(len(r.output_tokens) for r in results.values())
            outs = [tuple(results[i].output_tokens) for i in range(n_requests)]
            return toks / wall, outs
        finally:
            eng.stop()

    tps_xla, outs_xla = run_mode(False)
    tps_pallas, outs_pallas = run_mode(True)
    assert outs_pallas == outs_xla, (
        "greedy outputs DIVERGED kernel-on vs kernel-off — paged-decode "
        "kernel is wrong, refusing to report a speedup"
    )
    return {
        "pallas_step_latency_s": round(pallas_lat, 6),
        "xla_step_latency_s": round(xla_lat, 6),
        "kernel_step_speedup": round(xla_lat / pallas_lat, 3),
        "e2e_tokens_per_sec_pallas": round(tps_pallas, 2),
        "e2e_tokens_per_sec_xla": round(tps_xla, 2),
        "greedy_outputs_identical": True,
        "interpret": interpret,
        "batch": batch,
        "layers": layers,
    }


def chunked_prefill_bench(layers: int = 2, vocab: int = 2048, batch: int = 4,
                          prompt_len: int = 96, chunk: int = 32,
                          new_tokens: int = 16, n_requests: int = 6,
                          page_size: int = 16, max_seq_len: int = 256,
                          kernel_tq: int = 64, kernel_iters: int = 10):
    """Chunked-prefill flash kernel vs the XLA gather path
    (ops/pallas/chunked_prefill.py vs _pool_view + decode_attention_xla
    at Tq > 1) — the prefill-FLOPs sibling of paged_decode_bench.

    Two measurements:

    1. **raw kernel step latency** — one Tq=``kernel_tq`` chunk dispatch
       against a deep pool (qwen2 heads, ragged cache_len starts incl.
       mid-block), pallas vs XLA, jitted, mean over ``kernel_iters``;
    2. **e2e engine tokens/s** — long prompts warmed chunk-by-chunk
       (``chunked_prefill_tokens=chunk``) with ``use_pallas_prefill`` on
       vs off; greedy outputs HARD-asserted token-identical in-child.

    On CPU the kernel runs in interpret mode — mechanics + parity, not
    speed (the compiled TPU run is the perf signal)."""
    import threading

    import jax as _jax
    import jax.numpy as jnp
    import numpy as np

    from areal_tpu.api.cli_args import GenerationHyperparameters, JaxGenConfig
    from areal_tpu.inference.engine import GenerationEngine
    from areal_tpu.ops.attention import decode_attention_xla
    from areal_tpu.ops.pallas.chunked_prefill import chunked_prefill_attention

    interpret = _jax.default_backend() != "tpu"

    # --- raw kernel: one chunk dispatch off a churned pool ---
    nh, kh, d = 12, 2, 128
    bs = page_size
    nbt = max_seq_len // page_size
    nb = batch * nbt + 1
    rng = np.random.default_rng(0)
    dt = jnp.float32 if interpret else jnp.bfloat16
    tq = kernel_tq
    q = jnp.asarray(rng.normal(size=(batch, tq, nh, d)), dt)
    kp = jnp.asarray(rng.normal(size=(nb, bs, kh, d)), dt)
    vp = jnp.asarray(rng.normal(size=(nb, bs, kh, d)), dt)
    tbl = jnp.asarray(
        rng.permutation(nb - 1)[: batch * nbt].reshape(batch, nbt) + 1,
        jnp.int32,
    )
    # total_len = cache_len + tq with arbitrary (mid-block) cache_len
    lens = jnp.asarray(
        rng.integers(tq, max_seq_len, size=batch), jnp.int32
    )

    def xla_step(q, kp, vp, tbl, lens):
        view_k = kp[tbl].reshape(batch, nbt * bs, kh, d)
        view_v = vp[tbl].reshape(batch, nbt * bs, kh, d)
        return decode_attention_xla(q, view_k, view_v, lens)

    def pallas_step(q, kp, vp, tbl, lens):
        return chunked_prefill_attention(
            q, kp, vp, tbl, lens, interpret=interpret
        )

    def time_step(fn):
        # compile outside the timed window
        # arealint: disable-next-line=jit-in-loop,jit-per-call
        jf = _jax.jit(fn)
        _jax.block_until_ready(jf(q, kp, vp, tbl, lens))
        t0 = time.perf_counter()
        for _ in range(kernel_iters):
            out = jf(q, kp, vp, tbl, lens)
        _jax.block_until_ready(out)
        return (time.perf_counter() - t0) / kernel_iters

    xla_lat = time_step(xla_step)
    pallas_lat = time_step(pallas_step)

    # --- e2e: chunked warming through the engine, greedy identity ---
    model_cfg = qwen2_1p5b_cfg(layers, vocab=vocab)
    prompts = [
        rng.integers(1, vocab - 2, size=prompt_len).tolist()
        for _ in range(n_requests)
    ]
    gconfig = GenerationHyperparameters(
        max_new_tokens=new_tokens, min_new_tokens=new_tokens, greedy=True,
    )

    def run_mode(use_pallas: bool):
        eng = GenerationEngine(
            JaxGenConfig(
                max_batch_size=batch,
                max_seq_len=max_seq_len,
                prefill_chunk=chunk,
                chunked_prefill_tokens=chunk,
                page_size=page_size,
                # f32 so the identity assert sees no bf16 argmax-tie noise
                dtype="float32",
                use_pallas_prefill=use_pallas,
            ),
            model_config=model_cfg,
        )
        eng.start()
        try:
            done = threading.Event()
            results: dict = {}
            lock = threading.Lock()

            def cb(i, r):
                with lock:
                    results[i] = r
                    if len(results) >= n_requests:
                        done.set()

            t0 = time.perf_counter()
            for i, p in enumerate(prompts):
                eng.submit(
                    f"cp{i}", list(p), gconfig,
                    lambda r, i=i: cb(i, r),
                )
            assert done.wait(1200), "chunked-prefill bench timed out"
            wall = time.perf_counter() - t0
            toks = sum(len(r.output_tokens) for r in results.values())
            outs = [tuple(results[i].output_tokens) for i in range(n_requests)]
            warms = eng.chunked_prefill_count
            return toks / wall, outs, warms
        finally:
            eng.stop()

    tps_xla, outs_xla, _ = run_mode(False)
    tps_pallas, outs_pallas, warms = run_mode(True)
    assert warms > 0, "no chunked warming ran — the kernel was never hit"
    assert outs_pallas == outs_xla, (
        "greedy outputs DIVERGED kernel-on vs kernel-off — chunked-prefill "
        "kernel is wrong, refusing to report a speedup"
    )
    return {
        "pallas_step_latency_s": round(pallas_lat, 6),
        "xla_step_latency_s": round(xla_lat, 6),
        "kernel_step_speedup": round(xla_lat / pallas_lat, 3),
        "e2e_tokens_per_sec_pallas": round(tps_pallas, 2),
        "e2e_tokens_per_sec_xla": round(tps_xla, 2),
        "greedy_outputs_identical": True,
        "chunked_warmups": warms,
        "kernel_tq": tq,
        "interpret": interpret,
        "batch": batch,
        "layers": layers,
    }


def kv_quant_decode_bench(layers: int = 2, vocab: int = 2048, batch: int = 8,
                          prompt_len: int = 64, new_tokens: int = 32,
                          n_requests: int = 8, page_size: int = 16,
                          max_seq_len: int = 256, steps_per_call: int = 8,
                          kernel_iters: int = 10):
    """int8 KV-quantized Pallas decode vs the XLA dequant-gather path —
    the kv_quant="int8" x use_pallas_decode composition Rung B unlocked
    (before it, quantized pools silently degraded to the gather path).

    Two measurements:

    1. **raw kernel step latency** — one decode step on an int8 pool with
       per-(row, head) scale planes, in-kernel dequant vs XLA
       dequant-gather, jitted, mean over ``kernel_iters``;
    2. **e2e decode tokens/s** — kv_quant="int8" engines with
       ``use_pallas_decode`` on vs off, greedy outputs HARD-asserted
       token-identical in-child (same quantized pools both modes, so the
       argmax sees identical dequantized values).

    On CPU the kernel runs in interpret mode — mechanics + parity, not
    speed (the compiled TPU run is the perf signal; there the headline is
    halved KV bytes per step)."""
    import threading

    import jax as _jax
    import jax.numpy as jnp
    import numpy as np

    from areal_tpu.api.cli_args import GenerationHyperparameters, JaxGenConfig
    from areal_tpu.inference.engine import GenerationEngine
    from areal_tpu.models.lm import quantize_kv_rows
    from areal_tpu.ops.attention import decode_attention_xla
    from areal_tpu.ops.pallas.paged_attention import paged_decode_attention

    interpret = _jax.default_backend() != "tpu"

    # --- raw kernel: one decode step off an int8 pool ---
    nh, kh, d = 12, 2, 128
    bs = page_size
    nbt = max_seq_len // page_size
    nb = batch * nbt + 1
    rng = np.random.default_rng(0)
    rows_k = jnp.asarray(rng.normal(size=(nb, bs, kh, d)), jnp.float32)
    rows_v = jnp.asarray(rng.normal(size=(nb, bs, kh, d)), jnp.float32)
    kq, ks = quantize_kv_rows(rows_k)
    vq, vs = quantize_kv_rows(rows_v)
    dt = jnp.float32 if interpret else jnp.bfloat16
    q = jnp.asarray(rng.normal(size=(batch, 1, nh, d)), dt)
    tbl = jnp.asarray(
        rng.permutation(nb - 1)[: batch * nbt].reshape(batch, nbt) + 1,
        jnp.int32,
    )
    lens = jnp.asarray(
        rng.integers(1, max_seq_len, size=batch), jnp.int32
    )

    def xla_step(q, kq, vq, ks, vs, tbl, lens):
        # the gather path's dequant (_pool_view semantics)
        view_k = (
            kq[tbl].reshape(batch, nbt * bs, kh, d).astype(jnp.float32)
            * ks[tbl].reshape(batch, nbt * bs, kh)[..., None]
        ).astype(q.dtype)
        view_v = (
            vq[tbl].reshape(batch, nbt * bs, kh, d).astype(jnp.float32)
            * vs[tbl].reshape(batch, nbt * bs, kh)[..., None]
        ).astype(q.dtype)
        return decode_attention_xla(q, view_k, view_v, lens)

    def pallas_step(q, kq, vq, ks, vs, tbl, lens):
        return paged_decode_attention(
            q, kq, vq, tbl, lens, interpret=interpret,
            k_scale=ks, v_scale=vs,
        )

    def time_step(fn):
        # compile outside the timed window
        # arealint: disable-next-line=jit-in-loop,jit-per-call
        jf = _jax.jit(fn)
        _jax.block_until_ready(jf(q, kq, vq, ks, vs, tbl, lens))
        t0 = time.perf_counter()
        for _ in range(kernel_iters):
            out = jf(q, kq, vq, ks, vs, tbl, lens)
        _jax.block_until_ready(out)
        return (time.perf_counter() - t0) / kernel_iters

    xla_lat = time_step(xla_step)
    pallas_lat = time_step(pallas_step)

    # --- e2e: int8 engines, kernel on vs off, greedy identity ---
    model_cfg = qwen2_1p5b_cfg(layers, vocab=vocab)
    prompts = [
        rng.integers(1, vocab - 2, size=prompt_len).tolist()
        for _ in range(n_requests)
    ]
    gconfig = GenerationHyperparameters(
        max_new_tokens=new_tokens, min_new_tokens=new_tokens, greedy=True,
    )

    def run_mode(use_pallas: bool):
        eng = GenerationEngine(
            JaxGenConfig(
                max_batch_size=batch,
                max_seq_len=max_seq_len,
                prefill_chunk=64,
                page_size=page_size,
                decode_steps_per_call=steps_per_call,
                kv_quant="int8",
                # f32 so the identity assert sees no bf16 argmax-tie noise
                dtype="float32",
                use_pallas_decode=use_pallas,
            ),
            model_config=model_cfg,
        )
        assert eng.metrics_snapshot()["pallas_fallback_total"] == 0, (
            "int8 + use_pallas_decode fell back — Rung B regressed"
        )
        eng.start()
        try:
            done = threading.Event()
            results: dict = {}
            lock = threading.Lock()

            def cb(i, r):
                with lock:
                    results[i] = r
                    if len(results) >= n_requests:
                        done.set()

            t0 = time.perf_counter()
            for i, p in enumerate(prompts):
                eng.submit(
                    f"kq{i}", list(p), gconfig,
                    lambda r, i=i: cb(i, r),
                )
            assert done.wait(1200), "kv-quant decode bench timed out"
            wall = time.perf_counter() - t0
            toks = sum(len(r.output_tokens) for r in results.values())
            outs = [tuple(results[i].output_tokens) for i in range(n_requests)]
            scale_bytes = eng.serving_stats()["kv_pool_scale_bytes"]
            return toks / wall, outs, scale_bytes
        finally:
            eng.stop()

    tps_xla, outs_xla, _ = run_mode(False)
    tps_pallas, outs_pallas, scale_bytes = run_mode(True)
    assert outs_pallas == outs_xla, (
        "greedy outputs DIVERGED kernel-on vs kernel-off over the same "
        "int8 pools — in-kernel dequant is wrong, refusing to report a "
        "speedup"
    )
    return {
        "pallas_step_latency_s": round(pallas_lat, 6),
        "xla_step_latency_s": round(xla_lat, 6),
        "kernel_step_speedup": round(xla_lat / pallas_lat, 3),
        "e2e_tokens_per_sec_pallas": round(tps_pallas, 2),
        "e2e_tokens_per_sec_xla": round(tps_xla, 2),
        "greedy_outputs_identical": True,
        "kv_pool_scale_bytes": scale_bytes,
        "interpret": interpret,
        "batch": batch,
        "layers": layers,
    }


def weight_update_bench(layers: int = 28, chunk_mb: int = 512,
                        vocab: int = 151936):
    """Trainer->server weight-resync latency for the bench model (VERDICT
    r3 item 8): the /dev/shm same-host fast path vs HTTP safetensors
    streaming, both through the real server endpoints. The 'trainer' side
    is host numpy arrays shaped like the param tree (no second HBM copy —
    a 16GB chip cannot hold two 1.5B models plus staging)."""
    import asyncio
    import threading

    import numpy as np

    from areal_tpu.api.cli_args import InferenceEngineConfig, JaxGenConfig
    from areal_tpu.core.remote_inf_engine import RemoteInfEngine
    from areal_tpu.inference.engine import GenerationEngine
    from areal_tpu.inference.server import GenerationServer

    model_cfg = qwen2_1p5b_cfg(layers, vocab=vocab)
    eng = GenerationEngine(
        JaxGenConfig(
            max_batch_size=4, max_seq_len=512, prefill_chunk=128,
            dtype="bfloat16",
        ),
        model_config=model_cfg,
    )
    server = GenerationServer(eng)
    loop = asyncio.new_event_loop()
    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()
    port = asyncio.run_coroutine_threadsafe(
        server.start("127.0.0.1", 0), loop
    ).result(timeout=120)
    try:
        client = RemoteInfEngine(InferenceEngineConfig())
        client.addresses = [f"127.0.0.1:{port}"]

        # host-side trainer weights: same tree shapes, random bf16 bytes
        import jax as _jax

        shapes = _jax.tree.map(
            lambda x: (x.shape, str(x.dtype)), eng.params
        )
        rng = np.random.default_rng(0)

        def chunks():
            budget = chunk_mb * 1_000_000
            cur, size = {}, 0
            flat = []

            def walk(node, prefix):
                for k in sorted(node):
                    v = node[k]
                    path = f"{prefix}.{k}" if prefix else k
                    if isinstance(v, dict):
                        walk(v, path)
                    else:
                        flat.append((path, v))

            walk(shapes, "")
            for path, (shape, _dt) in flat:
                arr = rng.standard_normal(size=shape).astype(np.float32)
                if cur and size + arr.nbytes > budget:
                    yield cur
                    cur, size = {}, 0
                cur[path] = arr
                size += arr.nbytes
            if cur:
                yield cur

        def _total_bytes(node):
            out = 0
            for v in node.values():
                if isinstance(v, dict):
                    out += _total_bytes(v)
                else:
                    out += int(np.prod(v[0])) * 4
            return out

        total_mb = _total_bytes(shapes) / 1e6
        shm_lat = client.update_weights_from_shm(chunks(), next_version=1)
        http_lat = client.update_weights_from_tensors(chunks(), next_version=2)

        # disk path: trainer saves an HF safetensors checkpoint, servers
        # reload it via /update_weights_from_disk (the reference's slowest
        # but most portable resync; latency = save + fanned-out load)
        import shutil
        import tempfile

        from areal_tpu.api.io_struct import WeightUpdateMeta
        from areal_tpu.models import hf_io

        ckpt_dir = tempfile.mkdtemp(prefix="wu_disk_")
        try:
            t0 = time.perf_counter()
            hf_io.save_hf_params(eng.params, model_cfg, ckpt_dir)
            save_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            client.update_weights(
                WeightUpdateMeta(type="disk", path=ckpt_dir)
            )
            load_s = time.perf_counter() - t0
            disk_lat = save_s + load_s
        finally:
            shutil.rmtree(ckpt_dir, ignore_errors=True)
        return {
            "shm_sec": round(shm_lat, 3),
            "http_sec": round(http_lat, 3),
            "disk_sec": round(disk_lat, 3),
            "disk_save_sec": round(save_s, 3),
            "disk_load_sec": round(load_s, 3),
            "payload_mb_fp32": round(total_mb, 1),
            "layers": layers,
        }
    finally:
        asyncio.run_coroutine_threadsafe(server.stop(), loop).result(
            timeout=30
        )
        loop.call_soon_threadsafe(loop.stop)
        eng.stop()


def weight_sync_bench(layers: int = 2, vocab: int = 2048, chunk_mb: int = 64,
                      batch: int = 8, steps_per_call: int = 4,
                      max_seq_len: int = 512):
    """Zero-stall weight sync: tokens/s dip + fenced-window size while a
    tensor weight update streams into a LIVE decoding server, overlapped
    (pipelined staging, PR 5) vs fenced (pause -> update -> continue).

    The headline is ``weight_sync_stall_seconds`` — the engine-thread
    fence (commit dequeue -> version bump) the server reports in
    /model_info. Under the pipelined design it covers only the final
    pointer flip; the fenced comparison pays the whole transfer inside
    the pause window."""
    import asyncio
    import json as _json
    import threading
    import urllib.request

    import numpy as np

    from areal_tpu.api.cli_args import (
        GenerationHyperparameters,
        InferenceEngineConfig,
        JaxGenConfig,
    )
    from areal_tpu.core.remote_inf_engine import RemoteInfEngine
    from areal_tpu.inference.engine import GenerationEngine
    from areal_tpu.inference.server import GenerationServer

    model_cfg = qwen2_1p5b_cfg(layers, vocab=vocab)
    eng = GenerationEngine(
        JaxGenConfig(
            max_batch_size=batch, max_seq_len=max_seq_len, prefill_chunk=128,
            decode_steps_per_call=steps_per_call, dtype="bfloat16",
            page_size=max_seq_len,  # no mid-run table retrace
        ),
        model_config=model_cfg,
    )
    server = GenerationServer(eng)
    loop = asyncio.new_event_loop()
    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()
    port = asyncio.run_coroutine_threadsafe(
        server.start("127.0.0.1", 0), loop
    ).result(timeout=120)
    addr = f"127.0.0.1:{port}"

    client = RemoteInfEngine(InferenceEngineConfig())
    client.addresses = [addr]

    rng = np.random.default_rng(0)
    shapes = []

    def walk(node, prefix):
        for k in sorted(node):
            v = node[k]
            path = f"{prefix}.{k}" if prefix else k
            if isinstance(v, dict):
                walk(v, path)
            else:
                shapes.append((path, tuple(v.shape)))

    import jax as _jax

    walk(_jax.tree.map(lambda x: x, eng.params), "")
    payload_mb = sum(
        int(np.prod(s)) * 4 for _, s in shapes
    ) / 1e6

    def chunks():
        # own generator: this runs on the push loop's worker thread while
        # load_loop uses `rng` concurrently, and numpy Generators are not
        # thread-safe
        crng = np.random.default_rng(1)
        budget = chunk_mb * 1_000_000
        cur, size = {}, 0
        for path, shape in shapes:
            arr = crng.standard_normal(size=shape).astype(np.float32)
            if cur and size + arr.nbytes > budget:
                yield cur
                cur, size = {}, 0
            cur[path] = arr
            size += arr.nbytes
        if cur:
            yield cur

    def post(endpoint):
        req = urllib.request.Request(
            f"http://{addr}/{endpoint}", data=b"{}",
            headers={"Content-Type": "application/json"},
        )
        urllib.request.urlopen(req, timeout=60).read()

    def model_info():
        with urllib.request.urlopen(
            f"http://{addr}/model_info", timeout=10
        ) as resp:
            return _json.loads(resp.read())

    stop = threading.Event()

    def load_loop():
        """Keep ~batch requests in flight; finished/aborted requests are
        replaced so generated_tokens_total keeps moving."""
        sem = threading.Semaphore(batch)
        i = 0
        gcfg = GenerationHyperparameters(
            max_new_tokens=96, min_new_tokens=96, temperature=1.0
        )
        while not stop.is_set():
            sem.acquire()

            def cb(r, _s=sem):
                _s.release()

            try:
                eng.submit(
                    f"load-{i}",
                    rng.integers(1, vocab - 2, size=32).tolist(),
                    gcfg, cb,
                )
            except RuntimeError:
                return
            i += 1
            time.sleep(0.002)

    loader = threading.Thread(target=load_loop, daemon=True)
    loader.start()

    def tps_window(seconds: float) -> float:
        a = eng.generated_tokens_total
        t0 = time.perf_counter()
        time.sleep(seconds)
        return (eng.generated_tokens_total - a) / (time.perf_counter() - t0)

    try:
        # warmup: compile prefill/decode before any timed window
        deadline = time.time() + 300
        while eng.generated_tokens_total < 64 and time.time() < deadline:
            time.sleep(0.1)
        assert eng.generated_tokens_total >= 64, "decode load never warmed"

        steady_tps = tps_window(2.0)

        # --- overlapped: chunks stream + stage while decode dispatches ---
        a_tokens = eng.generated_tokens_total
        t0 = time.perf_counter()
        client.update_weights_from_tensors(chunks(), next_version=1)
        overlapped_update_s = time.perf_counter() - t0
        overlapped_window_tps = (
            (eng.generated_tokens_total - a_tokens) / overlapped_update_s
        )
        info = model_info()
        overlapped_stall_s = info["weight_sync_stall_seconds"]

        time.sleep(1.0)  # settle

        # --- fenced: classic pause -> full transfer -> continue ---
        a_tokens = eng.generated_tokens_total
        t0 = time.perf_counter()
        post("pause_generation")
        client.update_weights_from_tensors(chunks(), next_version=2)
        post("continue_generation")
        fenced_update_s = time.perf_counter() - t0
        fenced_window_tps = (
            (eng.generated_tokens_total - a_tokens) / fenced_update_s
        )
        return {
            "weight_sync_stall_seconds": round(overlapped_stall_s, 4),
            "fenced_stall_seconds": round(fenced_update_s, 3),
            "overlapped_update_s": round(overlapped_update_s, 3),
            "steady_tokens_per_sec": round(steady_tps, 1),
            "overlapped_window_tokens_per_sec": round(
                overlapped_window_tps, 1
            ),
            "fenced_window_tokens_per_sec": round(fenced_window_tps, 1),
            "payload_mb_fp32": round(payload_mb, 1),
            "layers": layers,
        }
    finally:
        stop.set()
        client._close_push_loop()
        asyncio.run_coroutine_threadsafe(server.stop(), loop).result(
            timeout=30
        )
        loop.call_soon_threadsafe(loop.stop)
        eng.stop()


def inflight_weight_swap_bench(layers: int = 2, vocab: int = 2048,
                               batch: int = 4, episode_tokens: int = 96,
                               steps_per_call: int = 4,
                               max_seq_len: int = 512):
    """In-flight weight swap via token-boundary interruption (ISSUE 19):
    a staged commit lands while every slot is mid-decode, interrupt-ON
    (interrupt_all at the next token boundary -> commit -> KV-retaining
    resume on the new version) vs the fenced baseline (wait for every
    in-flight episode to finish, then commit).

    The headline is **effective staleness**: mean tokens per episode
    decoded on the OLD weights after the swap was requested. Under
    interruption it is the token-boundary latency (~decode_steps_per_call
    tokens); fenced, it is the whole remaining generation length. Also
    reported: the swap's drain wall-time on vs off.

    HARD gates in-child: the staged weights equal the live ones, so every
    interrupted-and-resumed episode must be greedy token-identical to an
    unswapped reference, with versions spanning the commit, and the
    retained-KV ledger must return to zero."""
    import asyncio
    import threading
    import urllib.request  # noqa: F401  (parity with sibling children)

    import numpy as np

    from areal_tpu.api.cli_args import (
        GenerationHyperparameters,
        InferenceEngineConfig,
        JaxGenConfig,
    )
    from areal_tpu.api.io_struct import ModelRequest
    from areal_tpu.core.remote_inf_engine import RemoteInfEngine
    from areal_tpu.inference.engine import GenerationEngine
    from areal_tpu.inference.server import GenerationServer

    # float32: the identity gate compares token streams across an
    # interrupt/resume splice, so the compute must be bit-deterministic
    model_cfg = qwen2_1p5b_cfg(layers, vocab=vocab)
    eng = GenerationEngine(
        JaxGenConfig(
            max_batch_size=batch, max_seq_len=max_seq_len, prefill_chunk=128,
            decode_steps_per_call=steps_per_call, dtype="float32",
            page_size=max_seq_len,
            retained_kv_ttl_seconds=60.0,
        ),
        model_config=model_cfg,
    )
    server = GenerationServer(eng)
    loop = asyncio.new_event_loop()
    threading.Thread(target=loop.run_forever, daemon=True).start()
    port = asyncio.run_coroutine_threadsafe(
        server.start("127.0.0.1", 0), loop
    ).result(timeout=120)
    addr = f"127.0.0.1:{port}"
    client = RemoteInfEngine(InferenceEngineConfig())
    client.initialize(addr, train_data_parallel_size=1)

    rng = np.random.default_rng(0)
    prompts = [
        rng.integers(1, vocab - 2, size=16).tolist() for _ in range(batch)
    ]
    gcfg = GenerationHyperparameters(
        max_new_tokens=episode_tokens, greedy=True
    )

    named = {}

    def walk(node, prefix):
        for k in sorted(node):
            v = node[k]
            path = f"{prefix}.{k}" if prefix else k
            if isinstance(v, dict):
                walk(v, path)
            else:
                named[path] = np.asarray(v)

    walk(eng.params, "")

    def run_episodes(tag):
        results = [None] * batch

        def run(i):
            results[i] = client.generate(
                ModelRequest(
                    rid=f"{tag}-{i}", input_ids=prompts[i], gconfig=gcfg
                )
            )

        threads = [
            threading.Thread(target=run, args=(i,)) for i in range(batch)
        ]
        for t in threads:
            t.start()
        return threads, results

    def wait_mid_decode(min_tokens=3, timeout=300.0):
        """Block until every slot is decoding; returns rid -> tokens-out
        at that instant (the staleness baseline)."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            live = {
                s.rid: len(s.out_tokens)
                for s in eng.slots
                if s is not None and len(s.out_tokens) >= min_tokens
            }
            if len(live) >= batch:
                return live
            time.sleep(0.005)
        raise AssertionError("episodes never reached mid-decode")

    try:
        # reference: unswapped greedy episodes (compiles prefill/decode too)
        threads, refs = run_episodes("ref")
        for t in threads:
            t.join(timeout=600)
        assert all(
            r is not None and len(r.output_tokens) == episode_tokens
            for r in refs
        ), "reference episodes incomplete"

        # --- interrupt ON: token-boundary interrupt -> commit -> resume ---
        threads, on = run_episodes("on")
        len0 = wait_mid_decode()
        t0 = time.perf_counter()
        eng.stage_weight_chunk(named, version=1)
        eng.interrupt_all("swap")  # blocking: every slot answered
        eng.commit_staged_weights(1)
        swap_wall_on = time.perf_counter() - t0
        for t in threads:
            t.join(timeout=600)
        stale_on, resumed_span = [], 0
        for i, r in enumerate(on):
            assert r is not None and r.stop_reason in ("stop", "length")
            # greedy identity across the interrupt/commit/resume splice is
            # the rung's correctness gate
            assert r.output_tokens == refs[i].output_tokens, (
                f"episode {i} diverged across the in-flight swap"
            )
            vs = set(r.output_versions)
            assert 0 in vs and 1 in vs, (
                f"episode {i} versions {vs} do not span the commit"
            )
            resumed_span += 1
            stale_on.append(
                sum(1 for v in r.output_versions if v == 0)
                - len0[f"on-{i}"]
            )
        # the consumed retained entries must not leak
        deadline = time.time() + 30
        while (
            eng.serving_stats()["retained_kv_slots"] > 0
            and time.time() < deadline
        ):
            eng._wake.set()
            time.sleep(0.05)
        assert eng.serving_stats()["retained_kv_slots"] == 0

        # --- fenced OFF: wait for natural completion, then commit ---
        threads, off = run_episodes("off")
        len0 = wait_mid_decode()
        t0 = time.perf_counter()
        for t in threads:
            t.join(timeout=600)
        eng.stage_weight_chunk(named, version=2)
        eng.commit_staged_weights(2)
        swap_wall_off = time.perf_counter() - t0
        stale_off = []
        for i, r in enumerate(off):
            assert r is not None
            assert r.output_tokens == refs[i].output_tokens
            stale_off.append(len(r.output_tokens) - len0[f"off-{i}"])

        return {
            "effective_staleness_tokens": round(
                float(np.mean(stale_on)), 2
            ),
            "fenced_staleness_tokens": round(float(np.mean(stale_off)), 2),
            "staleness_reduction": round(
                float(np.mean(stale_off)) / max(float(np.mean(stale_on)), 0.5),
                1,
            ),
            "swap_wall_seconds": round(swap_wall_on, 3),
            "fenced_drain_wall_seconds": round(swap_wall_off, 3),
            "episodes_resumed_across_commit": resumed_span,
            "interrupts_total": eng.interrupts_total,
            "greedy_identity": True,
            "episodes": batch,
            "episode_tokens": episode_tokens,
            "layers": layers,
        }
    finally:
        client.destroy()
        asyncio.run_coroutine_threadsafe(server.stop(), loop).result(
            timeout=30
        )
        loop.call_soon_threadsafe(loop.stop)
        eng.stop()


def weight_propagation_bench(layers: int = 2, vocab: int = 2048,
                             hidden: int = 256, inter: int = 512,
                             chunk_mb: int = 2, batch: int = 4,
                             steps_per_call: int = 4, max_seq_len: int = 512,
                             n_servers: int = 4, fanout: int = 2):
    """Peer-to-peer weight propagation vs direct per-server streams at a
    simulated ``n_servers`` fleet (REAL GenerationServers, tiny model).

    Headline: the trainer-egress ratio relay/direct per commit — the
    fabric's contract is <= fanout/N + 0.1 (the trainer pays for the
    root streams only; every other server is fed by a peer relay hop).
    Also reported: commit wall latency both modes, the tokens/s window
    on a live decoding server during each update, and a mid-stream
    relay-parent kill (children fall back to direct push; zero torn
    commits). Greedy output identity relay-on vs relay-off is HARD
    asserted in-child — an egress win on diverging outputs would be a
    staging bug, not a speedup."""
    import asyncio
    import threading
    import types
    import urllib.request
    import json as _json

    import numpy as np

    from areal_tpu.api.cli_args import (
        GenerationHyperparameters,
        InferenceEngineConfig,
        JaxGenConfig,
    )
    from areal_tpu.core.remote_inf_engine import RemoteInfEngine
    from areal_tpu.inference.engine import GenerationEngine
    from areal_tpu.inference.server import GenerationServer
    from areal_tpu.models.config import TransformerConfig
    from areal_tpu.utils.metrics import DEFAULT_REGISTRY

    model_cfg = TransformerConfig(
        arch="qwen2", vocab_size=vocab, hidden_size=hidden,
        intermediate_size=inter, num_hidden_layers=layers,
        num_attention_heads=4, num_key_value_heads=2, head_dim=64,
        rope_theta=1e6, attention_bias=True, tie_word_embeddings=True,
    )
    import jax as _jax

    from areal_tpu.models.lm import init_params as _init_params

    engines = []
    servers = []
    loop = asyncio.new_event_loop()
    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()
    addrs = []
    for _ in range(n_servers):
        eng = GenerationEngine(
            JaxGenConfig(
                max_batch_size=batch, max_seq_len=max_seq_len,
                prefill_chunk=128, decode_steps_per_call=steps_per_call,
                dtype="float32", page_size=max_seq_len,
            ),
            model_config=model_cfg,
            params=_init_params(model_cfg, _jax.random.PRNGKey(0),
                                _jax.numpy.float32),
        )
        server = GenerationServer(eng)
        port = asyncio.run_coroutine_threadsafe(
            server.start("127.0.0.1", 0), loop
        ).result(timeout=120)
        engines.append(eng)
        servers.append(server)
        addrs.append(f"127.0.0.1:{port}")

    client = RemoteInfEngine(InferenceEngineConfig(request_retries=1))
    client.addresses = list(addrs)

    from areal_tpu.utils.wire import walk_named_leaves

    shapes = [
        (path, tuple(leaf.shape))
        for path, leaf in walk_named_leaves(engines[0].params)
    ]
    payload_bytes = sum(int(np.prod(s)) * 4 for _, s in shapes)

    def chunks(seed: int):
        crng = np.random.default_rng(seed)
        budget = chunk_mb * 1_000_000
        cur, size = {}, 0
        for path, shape in shapes:
            arr = crng.standard_normal(size=shape).astype(np.float32)
            if cur and size + arr.nbytes > budget:
                yield cur
                cur, size = {}, 0
            cur[path] = arr
            size += arr.nbytes
        if cur:
            yield cur

    def model_info(addr):
        with urllib.request.urlopen(
            f"http://{addr}/model_info", timeout=10
        ) as resp:
            return _json.loads(resp.read())

    def trainer_egress():
        return DEFAULT_REGISTRY.counter(
            "areal_weight_egress_bytes_total", labels=("source",)
        ).labels(source="trainer").value

    def greedy(eng, prompt, max_new=16):
        done = threading.Event()
        out = []
        eng.submit(
            f"greedy-{time.monotonic_ns()}", list(prompt),
            GenerationHyperparameters(
                max_new_tokens=max_new, min_new_tokens=max_new, greedy=True
            ),
            lambda r: (out.append(r), done.set()),
        )
        assert done.wait(120), "greedy probe timed out"
        return list(out[0].output_tokens)

    rng = np.random.default_rng(0)
    stop = threading.Event()

    def load_loop():
        sem = threading.Semaphore(batch)
        i = 0
        gcfg = GenerationHyperparameters(
            max_new_tokens=64, min_new_tokens=64, temperature=1.0
        )
        while not stop.is_set():
            sem.acquire()

            def cb(r, _s=sem):
                _s.release()

            try:
                engines[0].submit(
                    f"load-{i}",
                    rng.integers(1, vocab - 2, size=24).tolist(), gcfg, cb,
                )
            except RuntimeError:
                return
            i += 1
            time.sleep(0.002)

    loader = threading.Thread(target=load_loop, daemon=True)
    loader.start()

    def tps_window(fn):
        a = engines[0].generated_tokens_total
        t0 = time.perf_counter()
        fn()
        dt = max(time.perf_counter() - t0, 1e-6)
        return (engines[0].generated_tokens_total - a) / dt, dt

    class TearOn:
        def __init__(self, needle, n_ok):
            self.needle, self.n_ok, self.seen = needle, n_ok, 0

        def decide(self, url):
            if self.needle in url:
                self.seen += 1
                if self.seen > self.n_ok:
                    return types.SimpleNamespace(kind="disconnect")
            return None

    try:
        deadline = time.time() + 300
        while engines[0].generated_tokens_total < 64 and time.time() < deadline:
            time.sleep(0.1)
        assert engines[0].generated_tokens_total >= 64, "load never warmed"
        probe = rng.integers(1, vocab - 2, size=16).tolist()

        # --- DIRECT: per-server streams (the PR 5 baseline) -----------
        e0 = trainer_egress()
        direct_tps, direct_latency = tps_window(
            lambda: client.update_weights_from_tensors(chunks(1), 1)
        )
        egress_direct = trainer_egress() - e0
        assert all(model_info(a)["weight_version"] == 1 for a in addrs)
        greedy_direct = greedy(engines[0], probe)

        # --- RELAY: same chunk bytes through the propagation tree -----
        client.config.weight_propagation_enabled = True
        client.config.weight_propagation_fanout = fanout
        e0 = trainer_egress()
        relay_tps, relay_latency = tps_window(
            lambda: client.update_weights_from_tensors(chunks(1), 2)
        )
        egress_relay = trainer_egress() - e0
        assert all(model_info(a)["weight_version"] == 2 for a in addrs)
        greedy_relay = greedy(engines[0], probe)
        # HARD gate: identical chunk bytes -> identical weights -> the
        # relay hop must be token-invisible to greedy serving
        assert greedy_relay == greedy_direct, (
            "greedy outputs diverged relay-on vs relay-off"
        )
        # cross-fleet identity: every relay-fed server serves the exact
        # same function as the root the trainer fed directly
        fleet_outs = [greedy(e, probe) for e in engines]
        assert all(o == fleet_outs[0] for o in fleet_outs), (
            "relay-fed servers diverged from the root"
        )
        egress_ratio = egress_relay / max(egress_direct, 1.0)
        assert egress_ratio <= fanout / n_servers + 0.1, (
            f"trainer egress ratio {egress_ratio:.3f} exceeds "
            f"{fanout}/{n_servers} + 0.1"
        )

        # --- chaos: kill the first relay parent mid-stream ------------
        client._last_disk_update = ("/ckpt/rejoin", 3)
        client._chaos = TearOn(f"{addrs[0]}/relay_weights", n_ok=1)
        client.update_weights_from_tensors(chunks(2), 3)
        client._chaos = None
        versions = [model_info(a)["weight_version"] for a in addrs]
        # the dead parent stays cleanly at the OLD version; everyone
        # else (its children included, via direct fallback) commits —
        # nobody holds a half-applied tree
        torn = sum(1 for v in versions if v not in (2, 3))
        assert torn == 0, f"torn commits: {versions}"
        assert versions[0] == 2 and versions.count(3) == n_servers - 1, (
            versions
        )
        committed = [
            greedy(e, probe)
            for e, v in zip(engines, versions)
            if v == 3
        ]
        assert all(o == committed[0] for o in committed), (
            "fallback-fed children diverged after the parent kill"
        )
        # the dead parent still serves its old weights token-exactly
        assert greedy(engines[0], probe) == greedy_relay

        return {
            "trainer_egress_ratio": round(egress_ratio, 4),
            "egress_direct_mb": round(egress_direct / 1e6, 2),
            "egress_relay_mb": round(egress_relay / 1e6, 2),
            "payload_mb": round(payload_bytes / 1e6, 2),
            "direct_commit_s": round(direct_latency, 3),
            "relay_commit_s": round(relay_latency, 3),
            "direct_window_tokens_per_sec": round(direct_tps, 1),
            "relay_window_tokens_per_sec": round(relay_tps, 1),
            "n_servers": n_servers,
            "fanout": fanout,
            "propagation_depth": int(
                DEFAULT_REGISTRY.gauge(
                    "areal_weight_propagation_depth"
                ).value
            ),
            "parent_kill_torn_commits": torn,
            "greedy_identical": True,
        }
    finally:
        stop.set()
        client._close_push_loop()
        for server in servers:
            asyncio.run_coroutine_threadsafe(server.stop(), loop).result(
                timeout=30
            )
        loop.call_soon_threadsafe(loop.stop)


def reward_service_bench(n_episodes: int = 12, tokens_per_episode: int = 120,
                         token_time: float = 0.01, gen_stagger: float = 0.2,
                         wedged_frac: float = 0.5, wedge_hold: float = 8.0,
                         task_timeout: float = 1.0, workers: int = 4, **_):
    """Reward-execution rung: the SAME simulated rollout load — episodes
    generate tokens (async token steps, staggered lengths like a real
    batch) and then score an end-of-episode reward through the sandbox,
    with ``wedged_frac`` of the rewards WEDGED (snippet sleeping
    ``wedge_hold`` s; the episode's own await gives up per-episode) —
    executed three ways:

    - ``inprocess``: the pre-ISSUE-14 architecture — sandbox calls
      offloaded with ``run_in_executor(None, ...)`` onto the loop's
      default thread pool (shrunk to ``workers`` threads: pods run
      hundreds of workflows against ~32 default threads, same ratio). A
      wedged reward keeps its THREAD for the full sandbox wall even
      after the await times out, so healthy rewards starve behind it;
    - ``pooled``: the bounded SandboxWorkerPool (per-task wall deadline
      enforced by process-group kill) — a wedged reward is killed at
      ``task_timeout`` and its slot comes back;
    - ``service``: the same pool behind the reward-service HTTP replica,
      through RewardServiceClient.

    Metric per mode: rollout tokens/s = total generated tokens over the
    wall until every episode SETTLES (reward verdict included). Headline
    = pooled/inprocess ratio (higher is better); the flatness contract
    is pooled ≈ service ≈ the no-wedge baseline (rewards hide behind
    generation when verdicts arrive on deadline)."""
    import asyncio
    from concurrent.futures import ThreadPoolExecutor

    from areal_tpu.api.cli_args import RewardServiceConfig
    from areal_tpu.reward.sandbox import run_sandboxed
    from areal_tpu.reward_service.client import RewardServiceClient
    from areal_tpu.reward_service.pool import SandboxWorkerPool
    from areal_tpu.reward_service.service import RewardService

    FAST = "print(41 + 1)"
    WEDGED = f"import time\ntime.sleep({wedge_hold})"
    n_wedged = int(n_episodes * wedged_frac)
    total_tokens = n_episodes * tokens_per_episode

    async def episode(i, reward_call, wedged: bool):
        # staggered generation lengths: rewards trickle into the plane
        # like a real batch instead of arriving as one burst
        steps = tokens_per_episode
        extra = i * gen_stagger
        for t in range(steps):
            await asyncio.sleep(token_time + extra / steps)
        try:
            await asyncio.wait_for(
                reward_call(WEDGED if wedged else FAST),
                timeout=task_timeout * 4,
            )
        except asyncio.TimeoutError:
            pass  # per-episode failure verdict; the plane moves on
        return steps

    async def run_mode(reward_call, wedge: bool):
        t0 = time.monotonic()
        made = await asyncio.gather(
            *(
                episode(i, reward_call, wedge and i < n_wedged)
                for i in range(n_episodes)
            )
        )
        wall = time.monotonic() - t0
        return sum(made) / wall, wall

    def mode_inprocess():
        async def main():
            loop = asyncio.get_running_loop()
            loop.set_default_executor(ThreadPoolExecutor(max_workers=workers))

            def sandbox(code):
                # pre-fix semantics: the thread runs the sandbox's FULL
                # wall budget regardless of the caller having moved on
                return run_sandboxed(code, timeout=wedge_hold + 2)

            async def call(code):
                await asyncio.get_running_loop().run_in_executor(  # arealint: disable=unbounded-default-executor
                    None, lambda: sandbox(code)
                )
            return await run_mode(call, wedge=True)

        return asyncio.run(main())

    def mode_pooled(wedge: bool):
        pool = SandboxWorkerPool(
            num_workers=workers, default_timeout=task_timeout,
            kill_grace=0.5,
        )

        async def main():
            async def call(code):
                await pool.arun(code)

            return await run_mode(call, wedge=wedge)

        try:
            return asyncio.run(main())
        finally:
            pool.shutdown()

    def mode_service():
        cfg = RewardServiceConfig(
            num_workers=workers, task_timeout=task_timeout,
        )

        async def main():
            svc = RewardService(cfg)
            port = await svc.start("127.0.0.1", 0)
            cli = RewardServiceClient(cfg, addresses=[f"127.0.0.1:{port}"])

            async def call(code):
                await cli.aexecute_code(code)

            try:
                return await run_mode(call, wedge=True)
            finally:
                await cli.close()
                await svc.stop()

        return asyncio.run(main())

    base_tps, base_wall = mode_pooled(wedge=False)  # healthy-reward baseline
    pooled_tps, pooled_wall = mode_pooled(wedge=True)
    service_tps, service_wall = mode_service()
    inproc_tps, inproc_wall = mode_inprocess()
    return {
        "baseline_tokens_per_sec": round(base_tps, 1),
        "inprocess_tokens_per_sec": round(inproc_tps, 1),
        "pooled_tokens_per_sec": round(pooled_tps, 1),
        "service_tokens_per_sec": round(service_tps, 1),
        "pooled_vs_inprocess": round(pooled_tps / max(inproc_tps, 1e-9), 3),
        "service_vs_pooled": round(service_tps / max(pooled_tps, 1e-9), 3),
        "pooled_vs_baseline": round(pooled_tps / max(base_tps, 1e-9), 3),
        "inprocess_wall_s": round(inproc_wall, 2),
        "pooled_wall_s": round(pooled_wall, 2),
        "service_wall_s": round(service_wall, 2),
        "total_tokens": total_tokens,
    }


def elastic_fleet_bench(n_requests: int = 48, new_tokens: int = 16,
                        token_time: float = 0.02, max_servers: int = 3,
                        interarrival: float = 0.12, **_):
    """Elastic-fleet rung: a synthetic load spike (n_requests concurrent
    generations, one-at-a-time service per server) against a 1-server fleet
    with autoscaling ON vs OFF. The serving substrate is the deterministic
    sim server (areal_tpu/fleet/harness.py — real subprocesses, real HTTP,
    the same pure-function token stream), so the rung measures the
    CONTROL-plane value cleanly: queueing collapse under scale-out, with
    greedy outputs token-identical across modes (hard-asserted) and ZERO
    failed requests in either mode (hard-asserted — an autoscaler that
    drops requests while resizing has no result to report).

    The load is OPEN-LOOP (requests arrive every ``interarrival`` seconds,
    at a rate above one server's service capacity but below the scaled
    fleet's): a closed burst dispatched at t=0 pins every request to the
    boot server before any newcomer exists, measuring nothing — arrivals
    over time are what an autoscaler actually absorbs."""
    import asyncio
    import threading

    from areal_tpu.api.cli_args import (
        FleetConfig,
        GenerationHyperparameters,
        InferenceEngineConfig,
    )
    from areal_tpu.api.io_struct import ModelRequest
    from areal_tpu.core.remote_inf_engine import RemoteInfEngine
    from areal_tpu.fleet import harness
    from areal_tpu.fleet.controller import FleetController
    from areal_tpu.fleet.provider import LocalSubprocessProvider

    fc = FleetConfig(
        enabled=True, min_servers=1, max_servers=max_servers,
        breach_evaluations=1, scale_out_cooldown_seconds=0.0,
        scale_in_cooldown_seconds=0.0, queue_depth_high_per_server=1.0,
        queue_depth_low_per_server=0.2, ready_timeout_seconds=60.0,
        drain_grace_seconds=10.0,
    )
    argv = [
        sys.executable, harness.__file__, "--port", "{port}",
        "--token-time", str(token_time), "--max-concurrency", "1",
    ]
    prompts = [[1, 2, 3, i] for i in range(n_requests)]

    def run_mode(autoscale: bool):
        prov = LocalSubprocessProvider(argv_template=argv)
        client = None
        ctl = None
        try:
            boot = FleetController(
                RemoteInfEngine(InferenceEngineConfig(
                    experiment_name="bench-fleet-boot", trial_name="t",
                )),
                fc, provider=prov,
            )
            addrs = boot.bootstrap()
            client = RemoteInfEngine(InferenceEngineConfig(
                experiment_name="bench-fleet", trial_name="t",
                max_concurrent_rollouts=n_requests, consumer_batch_size=2,
                request_retries=2, cache_aware_routing=False,
                schedule_policy="least_loaded",
            ))
            client.initialize(addrs, train_data_parallel_size=1)
            ctl = FleetController(client, fc, provider=prov)
            ctl._members.update(boot._members)

            async def one(i, p):
                req = ModelRequest(
                    rid=f"r{i}", input_ids=list(p),
                    gconfig=GenerationHyperparameters(
                        max_new_tokens=new_tokens, greedy=True
                    ),
                )
                r = await client.agenerate(req)
                return r.output_tokens, r.latency

            async def load():
                try:
                    tasks = []
                    for i, p in enumerate(prompts):
                        tasks.append(asyncio.ensure_future(one(i, p)))
                        await asyncio.sleep(interarrival)
                    return await asyncio.gather(
                        *tasks, return_exceptions=True
                    )
                finally:
                    await client._close_session_for_current_loop()

            results = {}
            lt = threading.Thread(
                target=lambda: results.update(out=asyncio.run(load()))
            )
            t0 = time.monotonic()
            lt.start()
            sizes = [len(client.addresses)]
            while lt.is_alive():
                if autoscale:
                    ctl.step()
                    sizes.append(len(client.addresses))
                time.sleep(0.25)
            lt.join()
            wall = time.monotonic() - t0
            out = results["out"]
            failed = [r for r in out if isinstance(r, BaseException)]
            ok = [r for r in out if not isinstance(r, BaseException)]
            lats = sorted(lat for _, lat in ok)
            p95 = lats[int(0.95 * (len(lats) - 1))] if lats else 0.0
            digest = hash(tuple(tuple(toks) for toks, _ in ok))
            return {
                "failed": len(failed),
                "latency_p95_s": round(p95, 4),
                "wall_s": round(wall, 3),
                "max_fleet": max(sizes),
                "digest": digest,
            }
        finally:
            if ctl is not None:
                ctl.close()
            if client is not None:
                client.destroy()
            prov.close()

    off = run_mode(autoscale=False)
    on = run_mode(autoscale=True)
    # hard gates: an autoscaler may never drop a request, and resizing may
    # never perturb greedy outputs
    assert off["failed"] == 0 and on["failed"] == 0, (off, on)
    assert on["digest"] == off["digest"], "autoscaling changed greedy outputs"
    return {
        "latency_p95_speedup": round(
            off["latency_p95_s"] / max(on["latency_p95_s"], 1e-6), 3
        ),
        "latency_p95_on_s": on["latency_p95_s"],
        "latency_p95_off_s": off["latency_p95_s"],
        "wall_on_s": on["wall_s"],
        "wall_off_s": off["wall_s"],
        "max_fleet_on": on["max_fleet"],
        "failed_requests": on["failed"] + off["failed"],
        "greedy_identity": True,
        "n_requests": n_requests,
        "new_tokens": new_tokens,
        "token_time": token_time,
        "interarrival": interarrival,
    }


def disaggregated_serving_bench(n_requests: int = 8, prompt_len: int = 256,
                                new_tokens: int = 24,
                                interarrival: float = 0.25,
                                batch: int = 0, steps_per_call: int = 2,
                                **_):
    """Prefill/decode disaggregation rung (ISSUE 20): the same mixed
    open-loop load (prompt lengths staggered around ``prompt_len``)
    against two REAL model servers, colocated (both generalist, client
    single-pool) vs disaggregated (one prefill-role + one decode-role,
    KV shipped over /ship_kv -> /import_kv, decode driven on the decode
    server with zero re-prefill).

    The headline is the decode inter-token-latency p95 ratio
    colocated/disaggregated (higher is better): on a colocated server
    every arriving prompt's prefill steals engine iterations from
    running decodes, while a decode-role server never prefills — that
    isolation is the latency value the split buys, visible even on CPU.

    Hard gates in-child:
    - zero failed requests in either mode;
    - greedy outputs token-identical across modes (the split may move
      work, never change tokens);
    - every disaggregated request actually SHIPPED (a silent fallback to
      single-pool would measure nothing);
    - a staged weight commit landing on the decode pool between prefill
      and import fences with 412 -> counted fallback -> local re-prefill
      that is STILL token-identical (same-value weights, new version)."""
    import asyncio
    import threading

    import jax as _jax
    import jax.numpy as _jnp
    import numpy as np

    from areal_tpu.api.cli_args import (
        DisaggregationConfig,
        GenerationHyperparameters,
        InferenceEngineConfig,
        JaxGenConfig,
    )
    from areal_tpu.api.io_struct import ModelRequest
    from areal_tpu.core.remote_inf_engine import RemoteInfEngine
    from areal_tpu.inference.engine import GenerationEngine
    from areal_tpu.inference.server import GenerationServer
    from areal_tpu.models.config import tiny_config
    from areal_tpu.models.lm import init_params
    from areal_tpu.utils.metrics import DEFAULT_REGISTRY

    model_cfg = tiny_config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
    )
    # every pool needs a slot per in-flight request: the open-loop load
    # can pile the whole request set onto one pool (prefill holds pinned
    # retained KV until its ship lands; decode holds every running
    # sequence), and slot pressure would evict pinned entries — a real
    # production behavior, but here it would silently turn shipped
    # requests into fallbacks and poison the all-shipped hard gate
    batch = batch or n_requests

    def make_params():
        return init_params(model_cfg, _jax.random.PRNGKey(0), _jnp.float32)

    def serve(role: str):
        eng = GenerationEngine(
            JaxGenConfig(
                max_batch_size=batch, max_seq_len=2048, prefill_chunk=64,
                decode_steps_per_call=steps_per_call, dtype="float32",
                role=role,
            ),
            model_config=model_cfg,
            params=make_params(),
        )
        server = GenerationServer(eng)
        loop = asyncio.new_event_loop()
        t = threading.Thread(target=loop.run_forever, daemon=True)
        t.start()
        port = asyncio.run_coroutine_threadsafe(
            server.start("127.0.0.1", 0), loop
        ).result(timeout=120)

        def stop():
            asyncio.run_coroutine_threadsafe(server.stop(), loop).result(60)
            loop.call_soon_threadsafe(loop.stop)

        return f"127.0.0.1:{port}", eng, stop

    def ship_count(outcome: str) -> float:
        return DEFAULT_REGISTRY.counter(
            "areal_client_kv_ship_total", labels=("outcome",),
        ).labels(outcome=outcome).value

    # mixed load: deterministic prompts staggered around prompt_len
    rng = np.random.default_rng(7)
    prompts = [
        rng.integers(1, 127, size=prompt_len + (i % 4) * 32).tolist()
        for i in range(n_requests)
    ]

    def run_mode(disagg: bool):
        addr_a, eng_a, stop_a = serve("prefill" if disagg else "")
        addr_b, eng_b, stop_b = serve("decode" if disagg else "")
        client = RemoteInfEngine(InferenceEngineConfig(
            experiment_name="bench-disagg", trial_name="t",
            max_concurrent_rollouts=n_requests, consumer_batch_size=2,
            request_retries=2,
            disaggregation=DisaggregationConfig(enabled=disagg),
        ))
        client.initialize([addr_a, addr_b], train_data_parallel_size=1)
        try:
            async def one(i, p):
                req = ModelRequest(
                    rid=f"r{i}", input_ids=list(p),
                    gconfig=GenerationHyperparameters(
                        max_new_tokens=new_tokens,
                        min_new_tokens=new_tokens, greedy=True,
                    ),
                )
                r = await client.agenerate(req)
                return r.output_tokens, r.ttft, r.itl

            async def load():
                try:
                    tasks = []
                    for i, p in enumerate(prompts):
                        tasks.append(asyncio.ensure_future(one(i, p)))
                        await asyncio.sleep(interarrival)
                    return await asyncio.gather(
                        *tasks, return_exceptions=True
                    )
                finally:
                    await client._close_session_for_current_loop()

            # warm every engine's jit caches OUTSIDE the measured window
            # (colocated: one pinned request per server compiles prefill
            # + decode on both; disagg: two shipped requests — sized for
            # both pow2 import-block buckets the load will hit — compile
            # prefill on the prefill engine and import-scatter + decode
            # on the decode engine: exactly the work each pool does under
            # load, so no mid-measurement compile stalls ITL or pins
            # retained KV long enough to trigger pressure eviction)
            warm_sizes = (prompt_len, prompt_len + 96)

            async def warm():
                try:
                    if disagg:
                        for i, n in enumerate(warm_sizes):
                            await one(
                                f"warm{i}",
                                rng.integers(1, 127, size=n).tolist(),
                            )
                    else:
                        for i, a in enumerate((addr_a, addr_b)):
                            client._rid_to_address[f"rwarm{i}"] = a
                            await one(
                                f"warm{i}",
                                rng.integers(
                                    1, 127, size=warm_sizes[-1]
                                ).tolist(),
                            )
                finally:
                    await client._close_session_for_current_loop()

            asyncio.run(warm())
            shipped0 = ship_count("shipped")
            import0 = eng_b.kv_import_total
            t0 = time.monotonic()
            out = asyncio.run(load())
            wall = time.monotonic() - t0
            failed = [r for r in out if isinstance(r, BaseException)]
            assert not failed, f"failed requests ({'disagg' if disagg else 'colocated'}): {failed[:2]}"
            ok = [r for r in out if not isinstance(r, BaseException)]
            itls = sorted(v for _, _, itl in ok for v in itl)
            ttfts = sorted(t for _, t, _ in ok)

            def p95(xs):
                return xs[int(0.95 * (len(xs) - 1))] if xs else 0.0

            res = {
                "itl_p95_s": round(p95(itls), 4),
                "ttft_p95_s": round(p95(ttfts), 4),
                "tokens_per_sec": round(
                    sum(len(toks) for toks, _, _ in ok) / max(wall, 1e-6), 1
                ),
                "wall_s": round(wall, 3),
                "tokens": [toks for toks, _, _ in ok],
            }
            if disagg:
                # every request must have taken the shipped path: a
                # fallback measures the single-pool plane under a
                # disaggregated label
                shipped = ship_count("shipped") - shipped0
                assert shipped == n_requests, (
                    f"only {shipped}/{n_requests} requests shipped KV"
                )
                assert eng_b.kv_import_total - import0 == n_requests, (
                    eng_b.kv_import_total
                )
                res["shipped"] = int(shipped)

                # staged weight commit between prefill and import: bump
                # the decode pool to v1 with IDENTICAL weights — the next
                # ship must fence (412), fall back loudly, and still
                # produce the same greedy tokens
                flat = {}

                def walk(node, prefix=""):
                    for k in sorted(node):
                        v = node[k]
                        path = f"{prefix}.{k}" if prefix else k
                        if isinstance(v, dict):
                            walk(v, path)
                        else:
                            flat[path] = np.asarray(_jax.device_get(v))

                walk(eng_b.params)
                eng_b.update_weights_from_named_arrays(flat, version=1)
                fence0 = ship_count("fallback_version_fence")

                async def fenced():
                    try:
                        return await one("fence", prompts[0])
                    finally:
                        await client._close_session_for_current_loop()

                toks_f, _, _ = asyncio.run(fenced())
                assert ship_count("fallback_version_fence") == fence0 + 1, (
                    "weight commit between prefill and import did not "
                    "fence with 412"
                )
                assert toks_f == res["tokens"][0], (
                    "greedy identity broke across the staged weight commit"
                )
                res["fence_identity"] = True
            return res
        finally:
            client.destroy()
            stop_a()
            stop_b()

    colocated = run_mode(disagg=False)
    disagg = run_mode(disagg=True)
    assert disagg["tokens"] == colocated["tokens"], (
        "disaggregation changed greedy outputs"
    )
    return {
        "itl_p95_improvement": round(
            colocated["itl_p95_s"] / max(disagg["itl_p95_s"], 1e-6), 3
        ),
        "itl_p95_colocated_s": colocated["itl_p95_s"],
        "itl_p95_disagg_s": disagg["itl_p95_s"],
        "ttft_p95_colocated_s": colocated["ttft_p95_s"],
        "ttft_p95_disagg_s": disagg["ttft_p95_s"],
        "tokens_per_sec_colocated": colocated["tokens_per_sec"],
        "tokens_per_sec_disagg": disagg["tokens_per_sec"],
        "shipped": disagg["shipped"],
        "fence_identity": disagg["fence_identity"],
        "greedy_identity": True,
        "n_requests": n_requests,
        "prompt_len": prompt_len,
        "new_tokens": new_tokens,
        "interarrival": interarrival,
    }


def prefix_cache_bench(layers: int = 2, vocab: int = 2048,
                       group_size: int = 8, prompt_len: int = 256,
                       new_tokens: int = 32, turns: int = 3,
                       batch: int = 8, steps_per_call: int = 8,
                       max_seq_len: int = 1024, page_size: int = 64,
                       dtype: str = "bfloat16"):
    """Prefix-cache serving rung: the two workloads the radix cache exists
    for, cache on vs off, same seeds, greedy (so outputs are comparable
    token-for-token).

    1. **GRPO-shaped**: the SAME prompt submitted ``group_size`` times
       (the n-samples rollout pattern) — cache-off prefills the prompt
       group_size times; cache-on prefills once and clones.
    2. **Multi-turn**: a conversation that re-sends its growing prefix
       every turn plus a fresh user chunk — cache-off re-prefills the
       whole history per turn; cache-on pays ~only the new turn.

    Three modes keep the attribution honest:

    - ``radix``  — this PR's serving plane (radix cache + slot reuse on),
    - ``slot``   — the PRIOR default (slot-level clone/extension only):
      the baseline an operator upgrades from,
    - ``none``   — all prefix reuse off: what the workload costs raw.

    The multi-turn workload interleaves ``max_batch_size`` distraction
    prompts between turns so conversation slots get recycled — the regime
    where the slot tier loses its coverage and only the radix tier
    (which survives slot churn) still reuses the prefix.

    Headline: ``prefill_tokens_computed`` reduction on the GRPO workload
    vs ``none`` (the ISSUE acceptance bar), with the vs-prior-default
    reduction reported alongside; greedy output identity is asserted
    across ALL modes. Also reports time-to-first-token and window
    tokens/s per mode. CPU-runnable (rehearsal ladder).

    ``dtype`` defaults to bfloat16 for throughput realism, but the rung
    driver passes float32: the identity gate is a HARD assert, and bf16
    argmax near-ties (random-init tiny models) can flip between
    prefill-chunking regimes across cache modes — a false KV-corruption
    alarm. The headline metric (prefill tokens computed) is an exact count
    either way."""
    import threading

    import numpy as np

    from areal_tpu.api.cli_args import GenerationHyperparameters, JaxGenConfig
    from areal_tpu.inference.engine import GenerationEngine

    model_cfg = qwen2_1p5b_cfg(layers, vocab=vocab)
    rng = np.random.default_rng(0)
    group_prompt = rng.integers(1, vocab - 2, size=prompt_len).tolist()
    turn_chunks = [
        rng.integers(1, vocab - 2, size=max(16, prompt_len // 4)).tolist()
        for _ in range(turns)
    ]
    gconfig = GenerationHyperparameters(
        max_new_tokens=new_tokens, min_new_tokens=new_tokens, greedy=True,
    )

    churn_prompts = [
        rng.integers(1, vocab - 2, size=48).tolist() for _ in range(batch)
    ]

    def run_mode(radix: bool, slot_reuse: bool) -> dict:
        eng = GenerationEngine(
            JaxGenConfig(
                max_batch_size=batch,
                max_seq_len=max_seq_len,
                prefill_chunk=128,
                page_size=page_size,
                decode_steps_per_call=steps_per_call,
                dtype=dtype,
                enable_prefix_cache=radix,
                enable_prefix_reuse=slot_reuse,
            ),
            model_config=model_cfg,
        )
        eng.start()
        try:
            # warmup compiles prefill/decode outside the timed window
            warm = threading.Event()
            eng.submit(
                "warm", rng.integers(1, vocab - 2, size=32).tolist(),
                GenerationHyperparameters(
                    max_new_tokens=4, min_new_tokens=4, greedy=True
                ),
                lambda r: warm.set(),
            )
            assert warm.wait(600), "prefix-cache warmup timed out"
            base_prefill = eng.prefill_tokens_computed_total

            # --- GRPO-shaped: group_size x the same prompt ---
            done = threading.Event()
            results: list = []
            lock = threading.Lock()

            def cb(r):
                with lock:
                    results.append(r)
                    if len(results) >= group_size:
                        done.set()

            t0 = time.perf_counter()
            for i in range(group_size):
                eng.submit(f"g{i}", list(group_prompt), gconfig, cb)
            assert done.wait(600), "grpo workload timed out"
            grpo_wall = time.perf_counter() - t0
            grpo_prefill = eng.prefill_tokens_computed_total - base_prefill
            grpo_tokens = sum(len(r.output_tokens) for r in results)
            grpo_ttft = sorted(r.ttft for r in results)
            grpo_outputs = [tuple(r.output_tokens) for r in results]

            # --- multi-turn growing prefix, WITH slot churn between
            # turns (distraction prompts recycle every slot, so only a
            # cache that survives slot reassignment still reuses the
            # conversation prefix — the radix tier's reason to exist) ---
            def churn():
                n = len(churn_prompts)
                cd = threading.Event()
                seen = []

                def ccb(r, _s=seen, _d=cd):
                    _s.append(r)
                    if len(_s) >= n:
                        _d.set()

                for j, p in enumerate(churn_prompts):
                    eng.submit(
                        f"churn{j}-{time.monotonic_ns()}", list(p),
                        GenerationHyperparameters(
                            max_new_tokens=2, min_new_tokens=2, greedy=True
                        ),
                        ccb,
                    )
                assert cd.wait(600), "churn prompts timed out"

            base_prefill = eng.prefill_tokens_computed_total
            mt_prefill = 0
            convo = list(turn_chunks[0])
            turn_outputs = []
            mt_wall = 0.0
            for t in range(turns):
                if t:
                    convo = convo + list(turn_chunks[t])
                turn_done = threading.Event()
                out = {}

                def tcb(r, _d=turn_done, _o=out):
                    _o["r"] = r
                    _d.set()

                t0 = time.perf_counter()
                base_prefill = eng.prefill_tokens_computed_total
                eng.submit(f"turn{t}", list(convo), gconfig, tcb)
                assert turn_done.wait(600), "multi-turn workload timed out"
                mt_wall += time.perf_counter() - t0
                mt_prefill += (
                    eng.prefill_tokens_computed_total - base_prefill
                )
                convo = convo + out["r"].output_tokens
                turn_outputs.append(tuple(out["r"].output_tokens))
                churn()  # recycle the conversation's slot before next turn

            eng.record_serving_stats()  # StatsLogger surface (hit rates)
            stats = eng.serving_stats()
            return {
                "grpo_prefill_tokens": int(grpo_prefill),
                "grpo_wall_s": grpo_wall,
                "grpo_tokens_per_sec": grpo_tokens / grpo_wall,
                "grpo_ttft_p50_s": grpo_ttft[len(grpo_ttft) // 2],
                "grpo_ttft_max_s": grpo_ttft[-1],
                "grpo_outputs": grpo_outputs,
                "multiturn_prefill_tokens": int(mt_prefill),
                "multiturn_wall_s": mt_wall,
                "turn_outputs": turn_outputs,
                "hit_rate": stats["prefix_cache_hit_rate"],
            }
        finally:
            eng.stop()

    radix = run_mode(radix=True, slot_reuse=True)   # this PR's plane
    slot = run_mode(radix=False, slot_reuse=True)   # prior default
    none = run_mode(radix=False, slot_reuse=False)  # raw cost
    identical = (
        radix["grpo_outputs"] == slot["grpo_outputs"] == none["grpo_outputs"]
        and radix["turn_outputs"] == slot["turn_outputs"]
        == none["turn_outputs"]
    )
    # the correctness gate is HARD: a reduction headline measured on
    # diverging outputs is a KV-corruption bug wearing a speedup costume
    assert identical, (
        "greedy outputs diverged across prefix-cache modes: "
        f"radix={radix['grpo_outputs']!r} slot={slot['grpo_outputs']!r} "
        f"none={none['grpo_outputs']!r}"
    )
    for mode in (radix, slot, none):
        mode.pop("grpo_outputs")
        mode.pop("turn_outputs")

    def ratio(a, b):
        return round(a / max(1, b), 2)

    return {
        # ISSUE acceptance bar: cache on vs cache (all reuse) off
        "grpo_prefill_reduction": ratio(
            none["grpo_prefill_tokens"], radix["grpo_prefill_tokens"]
        ),
        "multiturn_prefill_reduction": ratio(
            none["multiturn_prefill_tokens"],
            radix["multiturn_prefill_tokens"],
        ),
        # honest upgrade delta vs the PRIOR default (slot tier already
        # covered GRPO groups while source slots were live; the radix
        # tier's own win shows under slot churn — the multi-turn number)
        "grpo_prefill_reduction_vs_prior": ratio(
            slot["grpo_prefill_tokens"], radix["grpo_prefill_tokens"]
        ),
        "multiturn_prefill_reduction_vs_prior": ratio(
            slot["multiturn_prefill_tokens"],
            radix["multiturn_prefill_tokens"],
        ),
        "greedy_outputs_identical": identical,
        "group_size": group_size,
        "prompt_len": prompt_len,
        "turns": turns,
        "mode_radix": {k: round(v, 4) if isinstance(v, float) else v
                       for k, v in radix.items()},
        "mode_slot_only": {k: round(v, 4) if isinstance(v, float) else v
                           for k, v in slot.items()},
        "mode_no_reuse": {k: round(v, 4) if isinstance(v, float) else v
                          for k, v in none.items()},
        "layers": layers,
    }


# ---------------------------------------------------------------------------
# Main ladder
# ---------------------------------------------------------------------------


def main():
    deadline = _T0 + WALL_S
    if not REHEARSAL:
        # wipe the partial file from any previous run; the REHEARSAL file
        # is deliberately append-only — it is the trajectory the
        # perf-regression sentinel baselines against
        try:
            os.unlink(PARTIAL_PATH)
        except OSError:
            pass

    info = probe_backend(deadline)
    chip = info["device_kind"]
    peak = info.get("peak_flops")

    # ---- rung 1: kernel compile validation (cheap, de-risks everything) ----
    # one child PER config: a single wedged/slow compile costs its own
    # timeout, not the whole rung (round-4 lesson: the monolithic child hit
    # the 900s cap with zero results recorded)
    kernels = {}
    # per-config timeouts AND a rung-level deadline: one wedged compile
    # costs its own child, and a fully wedged tunnel still can't starve
    # the PRIMARY sft rung of wall budget
    kernel_deadline = min(deadline, time.time() + 900.0)
    for kc in (KERNEL_CONFIGS_REHEARSAL if REHEARSAL else KERNEL_CONFIGS):
        cfg_timeout = min(
            480.0, remaining(kernel_deadline), remaining(deadline) - 120
        )
        # below ~4 min a compile timeout means "budget ran out", not
        # "kernel broken" — stop instead of recording spurious failures
        if remaining(deadline) < 300 or cfg_timeout < 240:
            log("kernel rung budget spent; moving on")
            break
        try:
            log(f"kernel config {kc['name']}")
            res = _run_child("kernels", {"configs": [kc]}, timeout=cfg_timeout)
            kernels.update(res)
        except Exception as e:  # noqa: BLE001
            log(f"kernel config {kc['name']} failed: {e}")
            kernels[kc["name"]] = {
                "ok": False,
                "error": str(e)[-400:],
                "wedged": isinstance(e, subprocess.TimeoutExpired),
            }
    if kernels:
        n_ok = sum(1 for v in kernels.values() if v.get("ok"))
        emit({
            "metric": "pallas_kernel_validation",
            "value": n_ok,
            "unit": f"of_{len(kernels)}_configs_compiled",
            "vs_baseline": None,
            "chip": chip,
            "detail": kernels,
        })

    # ---- rung 1.5: paged-decode kernel microbench (pallas vs XLA) ----
    # the serving engine's decode hot path; greedy kernel-on-vs-off output
    # identity is asserted inside the child (a speedup on diverging tokens
    # is a KV bug, not a result)
    if remaining(deadline) > 420:
        try:
            log("paged-decode kernel rung")
            pd_att = (
                dict(layers=2, vocab=2048, batch=8, prompt_len=64,
                     new_tokens=32, n_requests=8, page_size=16,
                     max_seq_len=256, kernel_iters=5)
                if REHEARSAL
                else dict(layers=28, vocab=151936, batch=48, prompt_len=128,
                          new_tokens=128, n_requests=48, page_size=64,
                          max_seq_len=512, kernel_iters=50)
            )
            pd = _run_child(
                "pgdec", pd_att,
                timeout=min(900.0, remaining(deadline) - 120),
            )
            emit({
                "metric": "paged_decode_attention",
                "value": pd["kernel_step_speedup"],
                "unit": "x_pallas_vs_xla_step_latency",
                "vs_baseline": None,
                "chip": chip,
                **pd,
            })
        except Exception as e:  # noqa: BLE001
            note_rung_failure("paged_decode_attention", "paged-decode", e)

    # ---- rung 1.6: chunked-prefill flash kernel (pallas vs XLA) ----
    # the serving engine's prefill-FLOPs path (chunked warming + radix
    # suffix-prefill); greedy identity asserted in-child like rung 1.5
    if remaining(deadline) > 420:
        try:
            log("chunked-prefill kernel rung")
            cp_att = (
                dict(layers=2, vocab=2048, batch=4, prompt_len=96,
                     chunk=32, new_tokens=16, n_requests=6, page_size=16,
                     max_seq_len=256, kernel_tq=64, kernel_iters=5)
                if REHEARSAL
                else dict(layers=28, vocab=151936, batch=8, prompt_len=2048,
                          chunk=512, new_tokens=64, n_requests=16,
                          page_size=64, max_seq_len=4096, kernel_tq=512,
                          kernel_iters=20)
            )
            cp = _run_child(
                "cprefill", cp_att,
                timeout=min(900.0, remaining(deadline) - 120),
            )
            emit({
                "metric": "chunked_prefill_attention",
                "value": cp["kernel_step_speedup"],
                "unit": "x_pallas_vs_xla_step_latency",
                "vs_baseline": None,
                "chip": chip,
                **cp,
            })
        except Exception as e:  # noqa: BLE001
            note_rung_failure(
                "chunked_prefill_attention", "chunked-prefill", e
            )

    # ---- rung 1.7: int8 KV-quantized decode (pallas vs XLA dequant) ----
    # the kv_quant x use_pallas_decode composition; in-kernel dequant
    # halves decode's KV bytes on TPU, identity asserted in-child
    if remaining(deadline) > 420:
        try:
            log("kv-quant decode kernel rung")
            kq_att = (
                dict(layers=2, vocab=2048, batch=8, prompt_len=64,
                     new_tokens=32, n_requests=8, page_size=16,
                     max_seq_len=256, kernel_iters=5)
                if REHEARSAL
                else dict(layers=28, vocab=151936, batch=48, prompt_len=128,
                          new_tokens=128, n_requests=48, page_size=64,
                          max_seq_len=512, kernel_iters=50)
            )
            kq = _run_child(
                "kvqdec", kq_att,
                timeout=min(900.0, remaining(deadline) - 120),
            )
            emit({
                "metric": "kv_quant_decode",
                "value": kq["kernel_step_speedup"],
                "unit": "x_pallas_vs_xla_step_latency",
                "vs_baseline": None,
                "chip": chip,
                **kq,
            })
        except Exception as e:  # noqa: BLE001
            note_rung_failure("kv_quant_decode", "kv-quant-decode", e)

    # ---- rung 2 (PRIMARY): SFT train throughput ladder ----
    # full model first (adam OOMs a 16GB chip at 1.5B even with bf16
    # moments -> adafactor); depth reduction is the last resort
    attempts = [
        # 4096-token microbatches hit the chip's matmul sweet spot; grad
        # accumulation over 2 of them amortizes the fixed per-step cost
        # (measured: 4.5k tok/s vs 4.3k single-mb, vs 3.7k one 8192 mb).
        # Lighter remat first: "mlp_saveable" keeps the two FLOPs-dominant
        # projections (~60% less backward recompute for 4.1GB at mb=4096);
        # "dots..." keeps every matmul output (fits at mb=2048). Both fall
        # back to full recompute on OOM.
        dict(layers=28, opt_type="adafactor", seqlen=4096, n_seqs=2,
             mb_tokens=4096,
             remat_policy="dots_with_no_batch_dims_saveable"),
        dict(layers=28, opt_type="adafactor", seqlen=4096, n_seqs=2,
             mb_tokens=4096, remat_policy="mlp_saveable"),
        dict(layers=28, opt_type="adafactor", seqlen=4096, n_seqs=2,
             mb_tokens=4096),
        dict(layers=28, opt_type="adafactor", seqlen=4096, n_seqs=1),
        dict(layers=28, opt_type="adafactor", seqlen=2048, n_seqs=2),
        dict(layers=14, opt_type="adamw", seqlen=2048, n_seqs=2),
        dict(layers=8, opt_type="adamw", seqlen=2048, n_seqs=2),
    ]
    if REHEARSAL:
        # same ladder shape (policy fallback preserved), CPU-sized
        attempts = [
            dict(layers=2, opt_type="adafactor", seqlen=512, n_seqs=2,
                 mb_tokens=512, vocab=2048,
                 remat_policy="dots_with_no_batch_dims_saveable"),
            dict(layers=2, opt_type="adamw", seqlen=256, n_seqs=2,
                 vocab=2048),
        ]
    tps = mfu_v = None
    used = None
    i = 0
    outage_retries = 0
    while i < len(attempts):
        att = attempts[i]
        if remaining(deadline) < 300:
            log("wall budget nearly spent; stopping sft ladder")
            break
        try:
            log(f"sft attempt: {att}")
            res = _run_child(
                "sft", att, timeout=min(1800.0, remaining(deadline) - 60)
            )
            tps, mfu_v = res["tps"], res["mfu"]
            used = att
            break
        except MemoryError:
            log(f"OOM at {att}; falling back")
            i += 1
        except subprocess.TimeoutExpired:
            # the documented wedge mode: backend init BLOCKS instead of
            # erroring, so the child hits its timeout. Distinguish a wedge
            # from a genuinely slow attempt with a cheap probe; only a
            # live backend demotes the ladder step
            if outage_retries < 4 and remaining(deadline) > 600:
                log(f"sft attempt timed out at {att}; probing backend")
                try:
                    pinfo = probe_backend(deadline)
                    if pinfo.get("probe_attempts", 1) > 1:
                        # probe had to retry -> the tunnel WAS wedged and
                        # has recovered; the timeout says nothing about
                        # this ladder step, so retry it (and only a
                        # CONFIRMED wedge consumes the retry budget)
                        outage_retries += 1
                        log("tunnel was wedged; retrying same attempt")
                        emit_wedged(METRIC, f"sft:{att}", None)
                    else:
                        log("backend live after timeout -> attempt was "
                            "slow; falling back")
                        i += 1
                except Exception as pe:  # noqa: BLE001
                    log(f"re-probe failed after timeout: {pe}")
                    i += 1
            else:
                log(f"sft attempt timed out at {att}; falling back")
                i += 1
        except RuntimeError as e:
            msg = str(e)
            if _is_outage(msg) and outage_retries < 4 and (
                remaining(deadline) > 600
            ):
                # a tunnel/backend outage says nothing about THIS ladder
                # step — wait for the chip to come back (probe_backend
                # backs off internally), then retry the same attempt
                outage_retries += 1
                log(
                    f"backend outage (retry {outage_retries}); re-probing "
                    "before resuming the ladder"
                )
                try:
                    probe_backend(deadline)
                except Exception as pe:  # noqa: BLE001
                    log(f"re-probe failed: {pe}")
                    i += 1
            else:
                log(f"sft attempt failed at {att}: {e}")
                i += 1

    primary = None
    if tps is not None:
        primary = {
            "metric": METRIC,
            "value": round(tps * used["layers"] / 28.0, 1),
            "unit": "tokens/s",
            "vs_baseline": round(mfu_v / REFERENCE_MFU, 3) if mfu_v else None,
            "mfu": round(mfu_v, 4) if mfu_v else None,
            "chip": chip,
            "chip_peak_tflops": peak / 1e12 if peak else None,
            "layers_used": used["layers"],
            "seqlen": used["seqlen"],
            "optimizer": used["opt_type"],
            "raw_tokens_per_sec": round(tps, 1),
            "probe_attempts": info.get("probe_attempts"),
        }
        emit(primary)

    # ---- rung 3: decode throughput ----
    decode_tps = None
    decode_attempts = [
        dict(n_requests=320, batch=160, steps_per_call=64),
        dict(n_requests=192, batch=96, steps_per_call=64),
        dict(n_requests=64, batch=48, steps_per_call=32),
    ]
    if REHEARSAL:
        decode_attempts = [
            dict(n_requests=8, batch=4, steps_per_call=4, prompt_len=32,
                 new_tokens=16, vocab=2048, max_seq_len=128),
        ]
    for datt in decode_attempts:
        if remaining(deadline) < 300:
            log("wall budget nearly spent; skipping decode")
            break
        try:
            log(f"decode attempt: {datt}")
            decode_tps = _run_child(
                "decode",
                dict(layers=(used or {"layers": 2 if REHEARSAL else 28})
                     ["layers"], **datt),
                timeout=min(1800.0, remaining(deadline) - 60),
            )["tps"]
            emit({
                "metric": "decode_tokens_per_sec",
                "value": round(decode_tps, 1),
                "unit": "tokens/s",
                "vs_baseline": None,
                "chip": chip,
                **datt,
            })
            break
        except Exception as e:  # noqa: BLE001
            log(f"decode bench failed at {datt}: {e}")
            if isinstance(e, subprocess.TimeoutExpired):
                emit_wedged(
                    "decode_tokens_per_sec", "decode",
                    getattr(e, "timeout", None),
                )

    # ---- rung 3.2: speculative decode — spec-on vs spec-off on a
    # repetitive-prompt workload (n-gram prompt-lookup regime), same
    # engine config, greedy so acceptance is deterministic. vs_baseline
    # here is the spec-on / spec-off throughput ratio. ----
    if remaining(deadline) > 420:
        satt = dict(
            n_requests=96, batch=48, steps_per_call=32, prompt_len=256,
            new_tokens=256, repetitive=True, greedy=True,
        )
        if REHEARSAL:
            satt = dict(
                n_requests=4, batch=2, steps_per_call=4, prompt_len=32,
                new_tokens=32, vocab=2048, max_seq_len=128,
                repetitive=True, greedy=True,
            )
        satt["layers"] = (used or {"layers": 2 if REHEARSAL else 28})[
            "layers"
        ]
        try:
            log(f"spec decode rung: {satt}")
            s_off = _run_child(
                "decode", {**satt, "spec_decode": "none"},
                timeout=min(1800.0, remaining(deadline) - 60),
            )
            s_on = _run_child(
                "decode", {**satt, "spec_decode": "ngram"},
                timeout=min(1800.0, remaining(deadline) - 60),
            )
            emit({
                "metric": "spec_decode_tokens_per_sec",
                "value": round(s_on["tps"], 1),
                "unit": "tokens/s",
                "vs_baseline": (
                    round(s_on["tps"] / s_off["tps"], 3)
                    if s_off["tps"] else None
                ),
                "spec_off_tokens_per_sec": round(s_off["tps"], 1),
                "spec_acceptance_rate": round(
                    s_on["spec_acceptance_rate"], 4
                ),
                "spec_steps": s_on["spec_steps"],
                "chip": chip,
                **satt,
            })
        except Exception as e:  # noqa: BLE001
            note_rung_failure("spec_decode_tokens_per_sec", "spec-decode", e)

    # ---- rung 3.25: tracing overhead — the PR 8 observability plane's
    # cost contract: full per-request tracing (spans + engine events) on
    # vs off on the same greedy decode workload; greedy output identity
    # is HARD-asserted across modes (a tokens/s delta measured on
    # diverging outputs would be meaningless), and the acceptance bar is
    # <= 3% tokens/s regression with tracing ON. ----
    if remaining(deadline) > 420:
        tatt = dict(
            n_requests=64, batch=32, steps_per_call=16, prompt_len=128,
            new_tokens=128, greedy=True,
        )
        if REHEARSAL:
            tatt = dict(
                n_requests=8, batch=4, steps_per_call=4, prompt_len=32,
                new_tokens=48, vocab=2048, max_seq_len=256, greedy=True,
            )
        tatt["layers"] = (used or {"layers": 2 if REHEARSAL else 28})[
            "layers"
        ]
        try:
            log(f"tracing overhead rung: {tatt}")
            tr_off = _run_child(
                "decode", {**tatt, "tracing": False},
                timeout=min(1200.0, remaining(deadline) - 60),
            )
            tr_on = _run_child(
                "decode", {**tatt, "tracing": True},
                timeout=min(1200.0, remaining(deadline) - 60),
            )
            # hard gate: greedy outputs must be token-identical with
            # tracing on — tracing must observe the system, never
            # perturb it
            assert tr_on["output_digest"] == tr_off["output_digest"], (
                "tracing changed greedy outputs: "
                f"{tr_on['output_digest']} != {tr_off['output_digest']}"
            )
            ratio = (
                tr_on["tps"] / tr_off["tps"] if tr_off["tps"] else None
            )
            # <=3% tokens/s acceptance bar, hard-gated on real hardware
            # runs only: CPU-rehearsal decode throughput jitters past 3%
            # both directions (the recorded rehearsal ratio is 1.031 —
            # tracing-on measured FASTER), so rehearsal reports the
            # ratio without gating on noise
            if not REHEARSAL and ratio is not None:
                assert ratio >= 0.97, (
                    "tracing on-cost exceeds the 3% tokens/s bar: "
                    f"on/off ratio {ratio:.4f}"
                )
            emit({
                "metric": "tracing_overhead",
                "value": round(tr_on["tps"], 1),
                "unit": "tokens/s_tracing_on",
                # >= 0.97 passes the <=3% overhead acceptance bar
                # (hard-asserted above on non-rehearsal runs)
                "vs_baseline": round(ratio, 4) if ratio else None,
                "tracing_off_tokens_per_sec": round(tr_off["tps"], 1),
                "ttft_on_s": round(tr_on["ttft_mean_s"], 4),
                "ttft_off_s": round(tr_off["ttft_mean_s"], 4),
                "greedy_identity": True,
                "chip": chip,
                **tatt,
            })
        except Exception as e:  # noqa: BLE001
            note_rung_failure("tracing_overhead", "tracing-overhead", e)

    # ---- rung 3.3: prefix cache — GRPO-shaped (same prompt x group) and
    # multi-turn growing-prefix workloads, cache on vs off. vs_baseline is
    # the prefill-token reduction factor on the GRPO workload; greedy
    # output identity is asserted inside the child. ----
    if remaining(deadline) > 420:
        # f32: the rung's headline is prefill-token COUNTS (dtype-exact) and
        # its correctness gate is a hard greedy-identity assert — in bf16 a
        # random-init argmax near-tie can flip between prefill-chunking
        # regimes and masquerade as KV corruption (observed when PR 7's
        # threefry alignment reshuffled init values)
        patt = dict(
            layers=(used or {"layers": 2 if REHEARSAL else 28})["layers"],
            group_size=8, prompt_len=512, new_tokens=64, turns=3, batch=8,
            dtype="float32",
        )
        if REHEARSAL:
            patt = dict(
                layers=2, vocab=2048, group_size=8, prompt_len=256,
                new_tokens=16, turns=3, batch=8, steps_per_call=4,
                max_seq_len=1024, page_size=64, dtype="float32",
            )
        try:
            log(f"prefix cache rung: {patt}")
            pc = _run_child(
                "pcache", patt, timeout=min(1200.0, remaining(deadline) - 60)
            )
            emit({
                "metric": "prefix_cache_prefill_reduction",
                "value": pc["grpo_prefill_reduction"],
                "unit": "x_fewer_prefill_tokens",
                "vs_baseline": pc["grpo_prefill_reduction"],
                "chip": chip,
                **pc,
            })
        except Exception as e:  # noqa: BLE001
            note_rung_failure(
                "prefix_cache_prefill_reduction", "prefix-cache", e
            )

    # ---- rung 3.5: weight-resync latency (shm vs http, VERDICT r3 #8) ----
    if remaining(deadline) > 420:
        try:
            log("weight-update rung")
            wu = _run_child(
                "wu",
                dict(layers=(used or {"layers": 2 if REHEARSAL else 28})
                     ["layers"],
                     **({"vocab": 2048} if REHEARSAL else {})),
                timeout=min(1200.0, remaining(deadline) - 60),
            )
            emit({
                "metric": "weight_update_latency",
                "value": wu["shm_sec"],
                "unit": "s_shm",
                "vs_baseline": None,
                "chip": chip,
                **wu,
            })
        except Exception as e:  # noqa: BLE001
            note_rung_failure("weight_update_latency", "weight-update", e)

    # ---- rung 3.6: zero-stall weight sync (overlapped vs fenced) ----
    if remaining(deadline) > 420:
        try:
            log("weight-sync (zero-stall) rung")
            ws = _run_child(
                "wsync",
                (dict(layers=2, vocab=2048, chunk_mb=8, batch=4)
                 if REHEARSAL
                 else dict(
                     layers=(used or {"layers": 28})["layers"],
                     chunk_mb=256,
                 )),
                timeout=min(1200.0, remaining(deadline) - 60),
            )
            emit({
                "metric": "weight_sync_stall_seconds",
                "value": ws["weight_sync_stall_seconds"],
                "unit": "s",
                # how much of the fenced stall the pipelined path eliminates
                "vs_baseline": (
                    round(
                        ws["fenced_stall_seconds"]
                        / max(ws["weight_sync_stall_seconds"], 1e-4),
                        1,
                    )
                ),
                "chip": chip,
                **ws,
            })
        except Exception as e:  # noqa: BLE001
            note_rung_failure("weight_sync_stall_seconds", "weight-sync", e)

    # ---- rung 3.62: in-flight weight swap — token-boundary interruption
    # vs fenced full-drain around a staged commit (ISSUE 19). value is
    # effective staleness in tokens/episode after the swap request; greedy
    # identity across the interrupt/commit/resume splice, commit-spanning
    # versions, and a zeroed retained-KV ledger are hard gates in the
    # child. ----
    if remaining(deadline) > 300:
        try:
            log("in-flight weight-swap rung")
            sw = _run_child(
                "swap",
                (dict(layers=2, vocab=2048, batch=4, episode_tokens=96)
                 if REHEARSAL
                 else dict(layers=4, vocab=8192, batch=8,
                           episode_tokens=256)),
                timeout=min(900.0, remaining(deadline) - 60),
            )
            assert sw["greedy_identity"]
            assert sw["episodes_resumed_across_commit"] >= 1
            emit({
                "metric": "inflight_weight_swap",
                "value": sw["effective_staleness_tokens"],
                "unit": "stale_tokens_per_episode",
                # how many stale tokens the fenced baseline pays per one
                # of ours
                "vs_baseline": sw["staleness_reduction"],
                "chip": chip,
                **{k: v for k, v in sw.items()
                   if k != "effective_staleness_tokens"},
            })
        except Exception as e:  # noqa: BLE001
            note_rung_failure(
                "inflight_weight_swap", "inflight-weight-swap", e
            )

    # ---- rung 3.65: peer-to-peer weight propagation — trainer egress
    # relay vs direct per-server streams at a simulated 4-server fleet
    # (real servers, tiny model; greedy identity + zero-torn-commit
    # parent-kill chaos are hard gates in the child). value is the
    # trainer-egress ratio — the contract is <= fanout/N + 0.1. ----
    if remaining(deadline) > 300:
        try:
            log("weight-propagation rung")
            wp = _run_child(
                "wprop",
                (dict(layers=2, vocab=2048, hidden=256, inter=512,
                      chunk_mb=2, batch=4, n_servers=4, fanout=2)
                 if REHEARSAL
                 else dict(layers=4, vocab=8192, hidden=512, inter=1024,
                           chunk_mb=32, batch=4, n_servers=4, fanout=2)),
                timeout=min(900.0, remaining(deadline) - 60),
            )
            assert wp["parent_kill_torn_commits"] == 0
            assert wp["trainer_egress_ratio"] <= (
                wp["fanout"] / wp["n_servers"] + 0.1
            )
            emit({
                "metric": "weight_propagation",
                "value": wp["trainer_egress_ratio"],
                "unit": "x_trainer_egress_relay_vs_direct",
                "vs_baseline": wp["trainer_egress_ratio"],
                "chip": chip,
                **{k: v for k, v in wp.items()
                   if k != "trainer_egress_ratio"},
            })
        except Exception as e:  # noqa: BLE001
            note_rung_failure(
                "weight_propagation", "weight-propagation", e
            )

    # ---- rung 3.7: elastic fleet — autoscaling on vs off under a load
    # spike (control-plane rung: sim serving substrate, real subprocesses +
    # HTTP; failed-request count and greedy identity are hard gates in the
    # child) ----
    if remaining(deadline) > 180:
        try:
            log("elastic fleet rung")
            ef = _run_child(
                "fleet",
                dict(
                    n_requests=36, new_tokens=16, token_time=0.02,
                    interarrival=0.12,
                )
                if REHEARSAL
                else dict(
                    n_requests=64, new_tokens=16, token_time=0.02,
                    interarrival=0.12,
                ),
                timeout=min(600.0, remaining(deadline) - 60),
            )
            emit({
                "metric": "elastic_fleet",
                "value": ef["latency_p95_speedup"],
                "unit": "x_latency_p95_autoscale_on_vs_off",
                "vs_baseline": None,
                "chip": chip,
                **{k: v for k, v in ef.items()
                   if k != "latency_p95_speedup"},
            })
        except Exception as e:  # noqa: BLE001
            note_rung_failure("elastic_fleet", "elastic-fleet", e)

    # ---- rung 3.75: prefill/decode disaggregation — mixed open-loop
    # load, colocated vs disaggregated over real model servers (ISSUE
    # 20). Greedy identity across modes AND across a staged weight
    # commit (412 fence -> loud local re-prefill), plus all-requests-
    # shipped, are hard gates in the child; the emitted value is the
    # decode ITL p95 ratio colocated/disaggregated (higher is better:
    # the decode pool's isolation from arriving prefills). ----
    if remaining(deadline) > 150:
        try:
            log("disaggregated serving rung")
            ds = _run_child(
                "disagg",
                dict(
                    n_requests=6, prompt_len=192, new_tokens=16,
                    interarrival=0.25,
                )
                if REHEARSAL
                else dict(
                    n_requests=12, prompt_len=256, new_tokens=24,
                    interarrival=0.2,
                ),
                timeout=min(600.0, remaining(deadline) - 60),
            )
            emit({
                "metric": "disaggregated_serving",
                "value": ds["itl_p95_improvement"],
                "unit": "x_decode_itl_p95_colocated_vs_disagg",
                "vs_baseline": None,
                "chip": chip,
                **{k: v for k, v in ds.items()
                   if k != "itl_p95_improvement"},
            })
        except Exception as e:  # noqa: BLE001
            note_rung_failure("disaggregated_serving", "disagg", e)

    # ---- rung 4: full GRPO step (async-RL headline metric) ----
    if remaining(deadline) > 420:
        try:
            log("grpo step rung")
            g = _run_child(
                "grpo", {"smoke": True} if REHEARSAL else {},
                timeout=min(1800.0, remaining(deadline) - 60)
            )
            emit({
                "metric": "grpo_step_sec",
                "value": g["step_sec"],
                "unit": "s",
                "vs_baseline": None,
                "chip": chip,
                **{k: v for k, v in g.items() if k != "step_sec"},
            })
        except Exception as e:  # noqa: BLE001
            note_rung_failure("grpo_step_sec", "grpo", e)

    # ---- rung 4.5: RL-health observatory overhead — the PR 13 cost
    # contract: the SAME colocated GRPO loop monitor-on vs monitor-off
    # (train-step wall + tokens/s); greedy output identity is HARD-asserted
    # in the child (the observatory must observe, never perturb). value is
    # the on/off tokens/s ratio — ~1.0 means the once-per-step host-side
    # telemetry is free at step granularity. ----
    if remaining(deadline) > 240:
        try:
            log("rl-health overhead rung")
            rh = _run_child(
                "rlh",
                dict(
                    layers=2, n_prompts=8, group_size=4, prompt_len=64,
                    new_tokens=32, steps=2, smoke=True,
                )
                if REHEARSAL
                else dict(
                    layers=14, n_prompts=8, group_size=4, prompt_len=128,
                    new_tokens=128, steps=2, smoke=False,
                ),
                timeout=min(1200.0, remaining(deadline) - 60),
            )
            # hard gate on real hardware; CPU-rehearsal step time jitters
            # past 5% both directions, so rehearsal reports without gating
            if not REHEARSAL:
                assert rh["tps_ratio_on_vs_off"] >= 0.95, (
                    "rl_health on-cost exceeds the 5% tokens/s bar: "
                    f"ratio {rh['tps_ratio_on_vs_off']}"
                )
            emit({
                "metric": "rl_health_overhead",
                "value": rh["tps_ratio_on_vs_off"],
                "unit": "x_tokens_per_sec_on_vs_off",
                "vs_baseline": rh["tps_ratio_on_vs_off"],
                "chip": chip,
                **{k: v for k, v in rh.items()
                   if k != "tps_ratio_on_vs_off"},
            })
        except Exception as e:  # noqa: BLE001
            note_rung_failure("rl_health_overhead", "rl-health", e)

    # ---- rung 4.6: reward-execution plane — in-process default-executor
    # offload vs the bounded sandbox pool vs the HTTP reward service,
    # under a concurrent wedged-reward flood (ISSUE 14). value is the
    # pooled/inprocess tokens/s ratio over the tool-using episodes; the
    # flatness contract is pooled ≈ service ≈ the unloaded baseline while
    # the legacy path collapses. Pure-CPU simulation (no model), so the
    # same numbers are the signal on rehearsal AND hardware. ----
    if remaining(deadline) > 120:
        try:
            log("reward service rung")
            rs = _run_child(
                "reward",
                dict(
                    n_episodes=6, tokens_per_episode=120, token_time=0.003,
                    wedged_frac=0.5, wedge_hold=8.0, task_timeout=1.0,
                    workers=4,
                ),
                timeout=min(300.0, remaining(deadline) - 30),
            )
            # the bounded plane must keep the rollout output flat: within
            # 40% of the unloaded baseline even while rewards wedge (the
            # legacy path typically lands under 20%)
            assert rs["pooled_vs_baseline"] >= 0.6, (
                "pooled reward plane dipped rollout tokens/s: "
                f"{rs['pooled_vs_baseline']} of baseline"
            )
            emit({
                "metric": "reward_service",
                "value": rs["pooled_vs_inprocess"],
                "unit": "x_tokens_per_sec_pooled_vs_inprocess",
                "vs_baseline": rs["pooled_vs_inprocess"],
                **{k: v for k, v in rs.items()
                   if k != "pooled_vs_inprocess"},
            })
        except Exception as e:  # noqa: BLE001
            note_rung_failure("reward_service", "reward", e)

    # ---- rung 4.7: full-system disaster-recovery drill (ISSUE 18) — a
    # correlated failure (trainer killed at a crash barrier, fleet servers
    # SIGKILLed mid-weight-stream, a reward replica wedged) must recover
    # with an identical step sequence, balanced counters, zero torn
    # commits, and the fleet reconciled; those invariants hard-gate in the
    # child. The emitted value is MTTR (kill-to-first-post-recovery-step,
    # lower is better) — pure-CPU simulation, so rehearsal numbers are the
    # live signal. Rehearsal runs the fast scenario; hardware runs the
    # full correlated one. ----
    if remaining(deadline) > 90:
        try:
            log("recovery drill rung")
            dr = _run_child(
                "drill",
                dict(
                    scenario="trainer-kill" if REHEARSAL
                    else "correlated-outage"
                ),
                timeout=min(300.0, remaining(deadline) - 30),
            )
            emit({
                "metric": "recovery_drill",
                "value": dr["mttr_seconds"],
                "unit": "s_mttr",
                "vs_baseline": None,
                "scenario": dr["scenario"],
                "recovered_at_step": dr["recovered_at_step"],
                "torn_commits": dr["torn_commits"],
                "counters_balanced": dr["counters_balanced"],
                "fleet_reconciled": dr["fleet_reconciled"],
                "repushed_servers": len(dr["repushed_servers"]),
            })
        except Exception as e:  # noqa: BLE001
            note_rung_failure("recovery_drill", "drill", e)

    if primary is not None:
        # repeat the primary as the FINAL line (drivers that take the last
        # parseable line get the headline metric)
        if decode_tps is not None:
            primary["decode_tokens_per_sec"] = round(decode_tps, 1)
        if REHEARSAL:
            primary = {**primary, "rehearsal": True}
        print(json.dumps(primary), flush=True)
    else:
        raise RuntimeError("all sft bench configurations failed")


def recovery_drill_bench(scenario: str = "trainer-kill") -> dict:
    """Full-system disaster drill (areal_tpu/drill): kill the trainer at a
    crash barrier (plus, per scenario, SIGKILL fleet servers mid-weight-
    stream and wedge reward replicas), recover, and measure MTTR
    (kill-to-first-post-recovery-step). The recovery INVARIANTS are hard
    gates in-child — a drill that recovers the wrong step sequence, tears
    a commit, or leaves the fleet unreconciled must fail the rung, not
    ship a pretty latency number."""
    import tempfile

    from areal_tpu.drill import run_scenario

    with tempfile.TemporaryDirectory(prefix="areal_drill_bench_") as d:
        report = run_scenario(scenario, d).to_json()
    assert report["passed"], f"drill invariants failed: {report['failures']}"
    assert report["torn_commits"] == 0, report
    assert report["counters_balanced"], report
    assert report["fleet_reconciled"], report
    assert 0 <= report["mttr_seconds"] < 20.0, (
        f"MTTR {report['mttr_seconds']}s out of budget"
    )
    return report


def _fail_record(e: Exception):
    """Parseable terminal record (round-1/2 lesson: a wedged tunnel must
    not leave only a stack trace). A probe that never resolved records
    the wedge-forensics shape the sentinel knows to skip."""
    if isinstance(e, BackendWedged):
        emit_wedged(METRIC, "backend_probe", WALL_S)
        return
    emit(
        {
            "metric": METRIC,
            "value": None,
            "unit": "tokens/s",
            "vs_baseline": None,
            "error": str(e)[:500],
        }
    )


def _child_main():
    # honor AREAL_PLATFORM (tests drive the children on CPU; the default
    # env-var-only JAX_PLATFORMS is NOT enough on this image — the TPU
    # plugin is force-registered by sitecustomize and backend init would
    # fight the tunnel for minutes)
    from areal_tpu.utils.device import apply_platform_env

    apply_platform_env()
    kind = sys.argv[1]
    att = json.loads(sys.argv[2]) if len(sys.argv) > 2 else {}
    if kind == "--probe-child":
        print(json.dumps(probe_child()))
    elif kind == "--kernels-child":
        print(json.dumps(kernels_child(att.get("configs"))))
    elif kind == "--sft-child":
        tps, mfu_v = sft_bench(**att)
        print(json.dumps({"tps": tps, "mfu": mfu_v}))
    elif kind == "--decode-child":
        print(json.dumps(decode_bench(**att)))
    elif kind == "--pgdec-child":
        print(json.dumps(paged_decode_bench(**att)))
    elif kind == "--cprefill-child":
        print(json.dumps(chunked_prefill_bench(**att)))
    elif kind == "--kvqdec-child":
        print(json.dumps(kv_quant_decode_bench(**att)))
    elif kind == "--pcache-child":
        print(json.dumps(prefix_cache_bench(**att)))
    elif kind == "--wu-child":
        print(json.dumps(weight_update_bench(**att)))
    elif kind == "--wsync-child":
        print(json.dumps(weight_sync_bench(**att)))
    elif kind == "--swap-child":
        print(json.dumps(inflight_weight_swap_bench(**att)))
    elif kind == "--wprop-child":
        print(json.dumps(weight_propagation_bench(**att)))
    elif kind == "--fleet-child":
        print(json.dumps(elastic_fleet_bench(**att)))
    elif kind == "--disagg-child":
        print(json.dumps(disaggregated_serving_bench(**att)))
    elif kind == "--reward-child":
        print(json.dumps(reward_service_bench(**att)))
    elif kind == "--grpo-child":
        from bench_grpo import grpo_step_bench

        print(json.dumps(grpo_step_bench(**att)))
    elif kind == "--drill-child":
        print(json.dumps(recovery_drill_bench(**att)))
    elif kind == "--rlh-child":
        from bench_grpo import rl_health_overhead_bench

        print(json.dumps(rl_health_overhead_bench(**att)))
    else:
        raise SystemExit(f"unknown child kind {kind}")


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1].endswith("-child"):
        _child_main()
    else:
        try:
            main()
        except Exception as e:  # backend outage etc. — emit a parseable
            # record instead of only a stack trace (round-1/2 failure mode:
            # the tunnel wedged and the driver recorded value:null)
            _fail_record(e)
            raise
        finally:
            if REHEARSAL:
                # every rehearsal run self-compares against the appended
                # trajectory and leaves a sentinel verdict line behind —
                # the "CPU rehearsal is the live perf signal" constraint,
                # with teeth
                append_rehearsal_verdict()
